"""In-kernel attention dropout (VERDICT r2 item 4).

Parity target: the reference's fused softmax+dropout with Philox RNG
(apex/contrib/csrc/multihead_attn/, setup.py:647).  The kernel's keep mask
is counter-based (stateless hash of seed and coordinates), so these tests
pin the two properties that design guarantees: exact determinism per seed
(forward AND backward), and the right statistics (keep fraction, mean/var
of kept activations, E[dropout] = identity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import _keep_mask, flash_attention

B, H, S, D = 1, 2, 256, 64
BLOCK = 128
RATE = 0.3


@pytest.fixture(autouse=True)
def _interpret_kernels(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "interpret")
    yield


@pytest.fixture
def qkv(rng):
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    return q, k, v


def _drop(q, k, v, seed, rate=RATE):
    return flash_attention(q, k, v, causal=True, dropout_rate=rate,
                           dropout_seed=seed, block_q=BLOCK, block_k=BLOCK)


def test_requires_seed(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(q, k, v, dropout_rate=0.1)
    with pytest.raises(ValueError, match="dropout_rate"):
        flash_attention(q, k, v, dropout_rate=1.5, dropout_seed=0)


def test_deterministic_per_seed(qkv):
    q, k, v = qkv
    a, b = _drop(q, k, v, 7), _drop(q, k, v, 7)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = _drop(q, k, v, 8)
    assert np.any(np.asarray(a) != np.asarray(c))


def test_backward_deterministic_per_seed(qkv):
    q, k, v = qkv

    def loss(q, k, v, seed):
        return jnp.sum(_drop(q, k, v, seed).astype(jnp.float32) ** 2)

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, 7)
    g2 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, 7)
    for a, b in zip(g1, g2):
        assert np.all(np.isfinite(np.asarray(a)))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    g3 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, 9)
    assert any(np.any(np.asarray(a) != np.asarray(b))
               for a, b in zip(g1, g3))


def test_keep_mask_statistics():
    """The counter hash must produce ~Bernoulli(1-rate) keep bits."""
    for rate in (0.1, 0.5):
        masks = [
            np.asarray(_keep_mask(jnp.int32(s), jnp.int32(3), jnp.int32(i),
                                  jnp.int32(j), 256, 256, rate))
            for s, i, j in [(0, 0, 0), (1, 0, 1), (2, 1, 0)]
        ]
        keep_frac = np.mean([m.mean() for m in masks])
        assert abs(keep_frac - (1.0 - rate)) < 0.01, (rate, keep_frac)
        # and tiles must not repeat each other (coordinate-dependent)
        assert not np.array_equal(masks[0], masks[1])


def test_kept_activation_statistics(qkv):
    """Mean/var of kept activations: with v = ones, each output row is the
    sum of kept, 1/(1-r)-rescaled probabilities — mean 1, variance pinned
    by the dropout rate (VERDICT's statistical-parity criterion)."""
    q, k, _ = qkv
    ones = jnp.ones((B, H, S, D), jnp.float32)
    rows = np.asarray(_drop(q, k, ones, 11)[:, :, S // 2:, 0]).ravel()
    # E[row] = 1 exactly; tolerance covers sampling noise over 256 rows
    assert abs(rows.mean() - 1.0) < 0.05, rows.mean()
    assert rows.std() > 0.05, "dropout had no effect"
    # no-dropout rows are exactly 1 (softmax sums to 1)
    base = np.asarray(flash_attention(
        q, k, ones, causal=True, block_q=BLOCK, block_k=BLOCK))[:, :, :, 0]
    np.testing.assert_allclose(base, 1.0, atol=1e-5)


@pytest.mark.slow
def test_expectation_matches_no_dropout(qkv):
    """E_seed[dropout output] -> no-dropout output (unbiasedness of the
    1/(1-r) rescaling), for values and gradients.

    Marked slow (~48 s: 24 seeded forwards + 24 seeded grads): this is
    a *statistical quality bar* over a seed ensemble, not a
    correctness witness — the deterministic per-seed forward/backward
    tests and the exact no-dropout parity above stay tier-1, same
    trade the sparsity permutation quality bar made for the paged
    tests (tier-1 runs against a hard wall-clock deadline)."""
    q, k, v = qkv
    base = np.asarray(flash_attention(q, k, v, causal=True,
                                      block_q=BLOCK, block_k=BLOCK))
    seeds = range(24)
    mean_out = np.mean([np.asarray(_drop(q, k, v, s)) for s in seeds], axis=0)
    scale = np.abs(base).mean()
    assert np.abs(mean_out - base).mean() / scale < 0.2

    def loss(q, seed):
        return jnp.sum(_drop(q, k, v, seed).astype(jnp.float32))

    gbase = np.asarray(jax.grad(
        lambda q: jnp.sum(flash_attention(q, k, v, causal=True,
                                          block_q=BLOCK, block_k=BLOCK)
                          .astype(jnp.float32)))(q))
    gmean = np.mean([np.asarray(jax.grad(loss)(q, s)) for s in seeds], axis=0)
    gscale = np.abs(gbase).mean()
    assert np.abs(gmean - gbase).mean() / gscale < 0.35


def test_fallback_path_dropout(qkv):
    """Odd shapes dispatch to the jnp fallback; dropout must work there with
    the same determinism contract."""
    q, k, v = qkv
    q, k, v = q[:, :, :100], k[:, :, :100], v[:, :, :100]  # 100 % 8 != 0
    a = flash_attention(q, k, v, causal=True, dropout_rate=RATE,
                        dropout_seed=5, block_q=64, block_k=64)
    b = flash_attention(q, k, v, causal=True, dropout_rate=RATE,
                        dropout_seed=5, block_q=64, block_k=64)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = flash_attention(q, k, v, causal=True, dropout_rate=RATE,
                        dropout_seed=6, block_q=64, block_k=64)
    assert np.any(np.asarray(a) != np.asarray(c))


def test_kernel_and_fallback_share_dropout_stream(qkv):
    """The Pallas path and the jnp fallback must realize the SAME dropout
    mask per (seed, coordinates) — a shape change that flips the kernel
    routing cannot silently change the dropout stream (r3 advisor
    finding).  Both paths now evaluate the identical counter hash, so
    outputs agree to float tolerance, not just in distribution."""
    from apex_tpu.ops.flash_attention import mha_reference

    q, k, v = qkv
    kern = np.asarray(_drop(q, k, v, 13))
    ref = np.asarray(mha_reference(q, k, v, causal=True, dropout_rate=RATE,
                                   dropout_seed=13))
    # identical keep masks → identical zero patterns and matching values
    np.testing.assert_allclose(kern, ref, rtol=2e-5, atol=2e-5)


def test_hash_chain_decorrelates_coordinates():
    """Chained-finalizer property: keep bits for neighbouring coordinate
    planes (g vs g+1, and shifted kpos) are uncorrelated — the structured
    collisions of a single shared premix round (r3 advisor finding) would
    show up as correlation ~1 on a plane pair."""
    m0 = np.asarray(_keep_mask(jnp.int32(5), jnp.int32(0), jnp.int32(0),
                               jnp.int32(0), 512, 512, 0.5)).astype(np.float64)
    m1 = np.asarray(_keep_mask(jnp.int32(5), jnp.int32(1), jnp.int32(0),
                               jnp.int32(0), 512, 512, 0.5)).astype(np.float64)
    corr_g = np.corrcoef(m0.ravel(), m1.ravel())[0, 1]
    assert abs(corr_g) < 0.02, corr_g
    # shift along kpos by one: adjacent-column masks must also decorrelate
    corr_k = np.corrcoef(m0[:, :-1].ravel(), m0[:, 1:].ravel())[0, 1]
    assert abs(corr_k) < 0.02, corr_k


def test_multihead_attn_routes_dropout_through_flash(rng):
    """SelfMultiheadAttn(training, dropout>0) must hit the flash kernel
    (no materialized [b*h, s, s] probabilities in the jaxpr)."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    s, b, e, h = 128, 2, 128, 2
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    mha = SelfMultiheadAttn(embed_dim=e, num_heads=h, dropout=0.4,
                            impl="fast")
    params = mha.init({"params": jax.random.PRNGKey(0),
                       "dropout": jax.random.PRNGKey(1)}, x,
                      is_training=False)

    def apply(x):
        return mha.apply(params, x, is_training=True,
                         rngs={"dropout": jax.random.PRNGKey(2)})

    jaxpr = str(jax.make_jaxpr(apply)(x))
    assert "flash" in jaxpr or "_fwd_kernel" in jaxpr or "pallas" in jaxpr
    # determinism with a fixed rng stream
    a, b_ = apply(x), apply(x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
