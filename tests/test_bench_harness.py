"""The bench retry/fallback harness must survive transient runtime errors.

Round 3's driver capture died on a single transient axon ``remote_compile``
error (BENCH_r03.json rc=1) because bench.py had no retry path.  These tests
pin the harness contract: bounded retries per config, fallback to the next
smaller model, at least one JSON line on stdout no matter what (flagship
first; extra configs and a combined final line when captured), and a
non-zero exit only when every primary config is exhausted.
"""

import io
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402


@pytest.fixture
def no_sleep(monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


def _run_main(monkeypatch, **kw):
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    kw.setdefault("model", "cpu-smoke")
    kw.setdefault("batch", None)
    kw.setdefault("steps", None)
    bench.main(**kw)
    sys.stdout = sys.__stdout__
    lines = [ln for ln in out.getvalue().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one JSON line, got {lines}"
    return json.loads(lines[0])


def test_retry_then_success(monkeypatch, no_sleep):
    calls = []

    def flaky(name, **kw):
        calls.append(name)
        if len(calls) < 3:
            raise RuntimeError("INTERNAL: remote_compile failed (transient)")
        return {"metric": f"gpt2_{name}", "value": 1.0}

    monkeypatch.setattr(bench, "run_config", flaky)
    result = _run_main(monkeypatch)
    assert result["attempts"] == 3
    assert result["fallback"] is False
    assert len(result["errors"]) == 2
    assert "remote_compile" in result["errors"][0]


def _tpu_lines(monkeypatch, **kw):
    """Run main(model=None) on a mocked TPU backend; return parsed lines."""
    monkeypatch.setattr(
        bench.jax, "devices",
        lambda *a: [type("D", (), {"platform": "tpu"})()])
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench.main(None, None, None, **kw)
    sys.stdout = sys.__stdout__
    return [json.loads(ln) for ln in out.getvalue().splitlines()
            if ln.strip()]


def test_fallback_to_next_config(monkeypatch, no_sleep):
    def flaky(name, **kw):
        if name == "large":
            raise RuntimeError("INTERNAL: stream broken")
        return {"metric": f"gpt2_{name}", "value": 1.0}

    monkeypatch.setattr(bench, "run_config", flaky)
    lines = _tpu_lines(monkeypatch, attempts_per_config=2)
    result = lines[0]
    assert result["metric"] == "gpt2_medium"
    assert result["fallback"] is True
    assert result["attempts"] == 3  # 2 failed large + 1 medium


def test_default_run_captures_extra_configs(monkeypatch, no_sleep):
    """The default run appends the 1.3B + Llama configs after the flagship
    (VERDICT r4 item 3) and ends with ONE combined line carrying them all."""
    calls = []

    def ok(name, **kw):
        calls.append(name)
        return {"metric": f"m_{name}", "value": 1.0}

    monkeypatch.setattr(bench, "run_config", ok)
    lines = _tpu_lines(monkeypatch)
    assert calls == ["large", "1.3b", "llama-1b", "resnet50"]
    # flagship line, then one refreshed combined line per captured extra —
    # NO standalone extra lines, so a kill at ANY line boundary leaves a
    # flagship-headlined record as the last complete line
    assert [ln["metric"] for ln in lines] == ["m_large"] * 4
    assert [len(ln.get("additional_configs", [])) for ln in lines] == [
        0, 1, 2, 3]
    combined = lines[-1]
    assert [r["metric"] for r in combined["additional_configs"]] == [
        "m_1.3b", "m_llama-1b", "m_resnet50"]


def test_extra_config_failure_does_not_fail_run(monkeypatch, no_sleep):
    """A dead extra config must not damage the captured flagship result."""
    def flaky(name, **kw):
        if name != "large":
            raise RuntimeError("INTERNAL: stream broken")
        return {"metric": f"m_{name}", "value": 1.0}

    monkeypatch.setattr(bench, "run_config", flaky)
    lines = _tpu_lines(monkeypatch)
    assert lines[0]["metric"] == "m_large"
    assert all("additional_configs" not in ln for ln in lines)


def test_hard_error_skips_retries(monkeypatch, no_sleep):
    """Deterministic failures (not in the transient class) must not burn
    the deadline re-proving themselves — one attempt, then next config."""
    calls = []

    def flaky(name, **kw):
        calls.append(name)
        if name == "large":
            raise TypeError("bad shape")  # hard: no marker, not assertion
        return {"metric": f"gpt2_{name}", "value": 1.0}

    monkeypatch.setattr(bench, "run_config", flaky)
    lines = _tpu_lines(monkeypatch, attempts_per_config=3)
    # no second 'large' attempt; extras still run after the fallback
    assert calls == ["large", "medium", "1.3b", "llama-1b", "resnet50"]
    assert lines[0]["fallback"] is True


def test_transient_markers_are_code_anchored(monkeypatch, no_sleep):
    """ADVICE r4: lowercase 'internal'/'stream'/'connection' words in a
    deterministic failure message must be classified hard (one attempt),
    not transient (full retry budget)."""
    calls = []

    def broken(name, **kw):
        calls.append(name)
        raise RuntimeError("lowering failed: internal stream connection op")

    monkeypatch.setattr(bench, "run_config", broken)
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    with pytest.raises(SystemExit):
        bench.main("cpu-smoke", None, None, attempts_per_config=3)
    sys.stdout = sys.__stdout__
    assert len(calls) == 1  # hard error: no retries burned


def test_all_fail_still_prints_json(monkeypatch, no_sleep):
    def broken(name, **kw):
        raise RuntimeError("INTERNAL: remote_compile failed")

    monkeypatch.setattr(bench, "run_config", broken)
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    with pytest.raises(SystemExit) as ei:
        bench.main("cpu-smoke", None, None, attempts_per_config=2)
    sys.stdout = sys.__stdout__
    assert ei.value.code == 1
    result = json.loads(out.getvalue().strip())
    assert result["ok"] is False
    assert result["attempts"] == 2
    assert len(result["errors"]) == 2


def test_deadline_stops_new_attempts(monkeypatch, no_sleep):
    # t_start, then the pre-attempt-2 deadline check (attempt 1 skips the
    # check because n_attempts == 0)
    clock = iter([0.0, 10_000.0, 10_000.0, 10_000.0])
    monkeypatch.setattr(bench.time, "monotonic", lambda: next(clock))

    def broken(name, **kw):
        raise RuntimeError("UNAVAILABLE: tunnel reset")  # transient class

    monkeypatch.setattr(bench, "run_config", broken)
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    with pytest.raises(SystemExit):
        bench.main("cpu-smoke", None, None, attempts_per_config=5,
                   deadline_s=100.0)
    sys.stdout = sys.__stdout__
    result = json.loads(out.getvalue().strip())
    # first attempt ran; the deadline blocked the rest
    assert result["attempts"] == 1
    assert any("deadline" in e for e in result["errors"])


def test_recovery_metrics_block():
    """The resilience-overhead block (ISSUE 1 satellite): checkpoint
    save/validate/restore timings + bytes, with leaf sampling under a
    byte budget so TPU-size states can't blow the bench deadline."""
    import jax.numpy as jnp

    tree = {"a": jnp.ones((64, 64), jnp.float32),
            "b": jnp.ones((128,), jnp.bfloat16)}
    r = bench._recovery_metrics(tree)
    assert r["bytes"] == 64 * 64 * 4 + 128 * 2
    assert r["sampled"] is False
    assert r["n_leaves"] == 2
    for k in ("save_ms", "validate_ms", "restore_ms"):
        assert r[k] >= 0.0
    # budget smaller than the tree: sampling kicks in but never to zero
    r2 = bench._recovery_metrics(tree, byte_budget=16)
    assert r2["sampled"] is True and r2["n_leaves"] == 1
    # a FIRST leaf bigger than the whole budget is sliced, not taken
    # whole — the budget is a hard cap (code-review finding)
    r3 = bench._recovery_metrics({"big": jnp.ones((64, 64), jnp.float32)},
                                 byte_budget=256)
    assert r3["sampled"] is True
    assert r3["bytes"] <= 256


def test_ckpt_async_metrics_block():
    """The async-checkpoint block (ISSUE 8): step-loop blocking ms per
    save for sync vs async, snapshot ms, background write ms, bytes —
    and the byte-identical on-disk guarantee.  The ≥5x blocking
    reduction is measured at the default (64 MB) size; at this toy size
    only sanity is asserted."""
    import jax.numpy as jnp

    tree = {"a": jnp.ones((128, 128), jnp.float32),
            "b": jnp.ones((64,), jnp.bfloat16)}
    r = bench._ckpt_async_metrics(tree, n_saves=2)
    assert r["ok"] is True
    assert r["bytes"] == 128 * 128 * 4 + 64 * 2
    assert r["sampled"] is False
    assert r["n_saves"] == 2
    for k in ("blocking_ms_per_save_sync", "blocking_ms_per_save_async",
              "snapshot_ms", "write_ms_background",
              "blocking_reduction_x"):
        assert r[k] > 0.0, k
    # async MUST be a scheduling change only: same bytes, same files
    assert r["bytes_identical"] is True
    # budget sampling rides the same helper as the recovery block
    r2 = bench._ckpt_async_metrics(tree, byte_budget=64, n_saves=1)
    assert r2["sampled"] is True and r2["bytes"] <= 64


def test_supervisor_metrics_block():
    """The robustness-tax block (ISSUE 2 satellite): watchdog arm/disarm
    per-step cost, heartbeat write latency, and the 2-failure transient
    retry path — host-only, sleeps zeroed."""
    r = bench._supervisor_metrics(n=200)
    assert r["ok"] is True
    for k in ("watchdog_arm_disarm_us_per_step", "heartbeat_write_ms",
              "retry_2fail_recovered_ms"):
        assert r[k] > 0.0, k
    # arm/disarm is attribute swaps: if it ever costs more than 1 ms a
    # step, the watchdog became part of the problem it measures
    assert r["watchdog_arm_disarm_us_per_step"] < 1000.0


def test_elastic_metrics_block():
    """The elastic-restart block (ISSUE 3 satellite): sharded save on
    (dp=4, tp=2), reshard-restore onto dp=2 and dp=8, and the
    steady-state replica-hash verify pass — all on the suite's
    8-virtual-CPU-device mesh."""
    r = bench._elastic_metrics(rows=64, cols=64)
    assert r["ok"] is True
    assert r["bytes"] == 64 * 64 * 4 + 64 * 4
    # tp=2 cuts w and b into 2 shards each at save time
    assert r["n_shards"] == 4
    for k in ("save_dp4_ms", "restore_dp2_ms", "restore_dp8_ms",
              "verify_replicas_ms"):
        assert r[k] > 0.0, k


@pytest.mark.slow   # ~15 s: follows the spec/prefix/paged block-test
# precedent — every serving claim the block grades has a direct tier-1
# witness in test_serving*.py; the block itself runs in the slow lane
def test_serving_metrics_block():
    """The serving block (ISSUE 4 + ISSUE 7 satellites): prefill
    tokens/s, per-token decode latency, continuous-batching throughput
    at 1/4/8 streams with staggered arrivals, and the mixed-length
    bucketed-vs-padded comparison — plus BOTH compile-count regression
    guards (ONE decode compile after warmup; prefill compiles bounded
    by the bucket table)."""
    r = bench._serving_metrics(decode_tokens=12, prompt_len=4,
                               prefill_len=32, max_len=64, slots=4,
                               mixed_streams=4, mixed_decode_tokens=2,
                               mixed_attempts=1)
    assert r["ok"] is True
    assert r["prefill_tokens_per_s"] > 0.0
    assert r["decode_ms_per_token"] > 0.0
    assert set(r["throughput_tokens_per_s"]) == {"1", "4", "8"}
    for tps in r["throughput_tokens_per_s"].values():
        assert tps > 0.0
    assert r["speedup_4_vs_sequential"] > 0.0
    # the decode step function must compile exactly once per engine no
    # matter how streams arrive — retraces would be the recompile tax
    # the slotted cache exists to eliminate
    assert r["decode_compiles_after_warmup"] == 1
    # the prefill path's compile count is bounded by the bucket table —
    # a per-prompt-length retrace would blow straight through this
    assert r["prefill_buckets"] == [16, 32]
    assert 1 <= r["prefill_compiles"] <= len(r["prefill_buckets"])
    # the mixed-length comparison runs and reports a sane ratio (the
    # >= 1.5x acceptance bar is measured at the default, bigger sizes —
    # at this toy size the per-dispatch host tax flattens the ratio)
    mixed = r["mixed"]
    assert len(mixed["prompt_lens"]) == 4
    assert all(1 <= n <= 32 for n in mixed["prompt_lens"])
    assert mixed["tokens_per_s_bucketed"] > 0.0
    assert mixed["tokens_per_s_padded"] > 0.0
    assert mixed["speedup_bucketed_vs_padded"] > 0.0
    assert r["config"]["slots"] == 4


@pytest.mark.slow   # ~10 s: follows the spec/prefix/paged block-test
# precedent — tp serving itself stays witnessed by tests/test_serving_tp.py
# (stream identity, compile guards) and the block's grading by
# tests/test_bench_compare.py golden fixtures
def test_serving_tp_metrics_block():
    """The tensor-parallel serving block (ISSUE 15): tp=1 vs tp=2
    decode ms/token and aggregate tokens/s over one warmed engine pair,
    the stream-identity witness, and the compile-count guards on BOTH
    engines — sharding must not add a single extra compile to any
    program family."""
    r = bench._serving_tp_metrics(decode_tokens=8, prompt_len=8,
                                  prefill_len=16, max_len=48, slots=2,
                                  tp_size=2)
    assert r["ok"] is True, r
    # the acceptance witness: greedy streams token-identical across
    # mesh widths (raw logits are argmax-tier, documented deviation)
    assert r["streams_identical"] is True
    for side in ("tp1", "tp2"):
        assert r[side]["decode_ms_per_token"] > 0.0
        assert r[side]["aggregate_tokens_per_s"] > 0.0
        # the compile-count regression guards, sharded and unsharded
        assert r[side]["decode_compiles"] == 1, r
        assert r[side]["prefill_compiles"] == 1
    assert r["tp_vs_single_ratio"] > 0.0
    assert r["config"]["tp"] == 2


@pytest.mark.slow   # ~15 s: bench-harness plumbing stays witnessed by
# test_serving_metrics_block / _slo / _tp; spec exactness and accept-
# rate claims keep their tier-1 witnesses in test_serving_spec.py
def test_serving_spec_metrics_block():
    """The speculative-decode block (ISSUE 9): spec-vs-plain greedy
    decode tokens/s on an acceptance-friendly repetitive workload
    (bar >= 1.8x) and an adversarial random-token workload (bar >=
    1.0x — the fall-back path must not regress), with the exactness
    witness (streams token-identical every attempt) and BOTH
    compile-count regression guards: verify compiles bounded by the
    draft bucket table, decode compiles == 1 unchanged."""
    r = bench._serving_spec_metrics(attempts=1)
    assert r["ok"] is True
    # exactness is asserted inside the block on EVERY attempt — a
    # speedup from a diverged stream would be a lie, not a win
    assert r["streams_identical"] is True
    # the ISSUE-9 acceptance bars
    assert r["speedup_repetitive"] >= 1.8, r
    assert r["speedup_adversarial"] >= 1.0, r
    # compile-count guards: bounded by the draft bucket table, and the
    # batched decode step still compiles exactly once
    assert r["draft_buckets"] == [1, 2, 4, 8]
    assert 1 <= r["verify_compiles"] <= len(r["draft_buckets"])
    assert r["decode_compiles"] == 1
    for name in ("repetitive", "adversarial"):
        w = r["workloads"][name]
        assert w["tokens_per_s_plain"] > 0.0
        assert w["tokens_per_s_spec"] > 0.0
        assert w["verify_dispatches"] > 0
        assert 0 <= w["accepted"] <= w["drafted"]
        # even a fully-rejected verify emits its bonus token, so the
        # speculative path never amortizes below one token/dispatch
        assert w["tokens_per_dispatch"] >= 1.0
        assert 0.0 <= w["accept_rate"] <= 1.0
    # the friendly workload must actually accept more than the
    # adversarial one — otherwise "repetitive" is mislabeled
    assert (r["workloads"]["repetitive"]["accept_rate"]
            >= r["workloads"]["adversarial"]["accept_rate"])


@pytest.mark.slow   # ~16 s: block plumbing witnessed by
# test_serving_metrics_block; the prefix hit/identity claims keep
# their tier-1 witnesses in test_serving_prefix.py
def test_serving_prefix_metrics_block():
    """The cross-request prefix-caching block (ISSUE 10): aggregate
    prefill tokens/s for 8 requests sharing a long system prompt —
    caching off vs cold cache vs warm cache — plus a zero-overlap
    workload where the cache can only cost.  Bars: warm >= 2x cold on
    the shared-prefix workload, no regression (>= 1.0x best-of-N)
    without overlap; streams token-identical across off/cold/warm on
    every attempt (the speedup is elided prefill, never drift); and
    the compile guards — restore compiles bounded by the prefill
    bucket table, decode compiles == 1 untouched.

    The zero-overlap bar is "no regression within the harness's own
    measured noise floor": copy-based capture has a real but sub-noise
    cost (~0.5-1% on a prefill-only drain at this scale — see the
    block's docstring and PERF_NOTES), so the block compares medians
    and measures the wider of the two pools' own spreads as the
    yardstick; a genuine regression is a consistent gap between
    tight pools and fails.  attempts=3
    (the default) keeps the pooled medians robust to one slow drain —
    at attempts=2 the 2-sample on-side median is a mean, and a single
    scheduler hiccup flaked the bar."""
    r = bench._serving_prefix_metrics()
    assert r["ok"] is True
    # exactness is asserted inside the block on EVERY attempt — a
    # speedup from a diverged stream would be a lie, not a win
    assert r["streams_identical"] is True
    shared = r["shared_prefix"]
    # the ISSUE-10 acceptance bars
    assert shared["speedup_warm_vs_cold"] >= 2.0, r
    zero = r["zero_overlap"]
    assert zero["no_regression_within_noise"] is True, r
    # hard floor: a sub-noise capture tax is tolerated, a real
    # slowdown is not, no matter how noisy the host claims to be
    assert zero["ratio_on_vs_off"] >= 0.9, r
    for k in ("prefill_tokens_per_s_off", "prefill_tokens_per_s_cold",
              "prefill_tokens_per_s_warm"):
        assert shared[k] > 0.0, k
    # a hit restores the shared tokens, so a warm admission must also
    # beat the caching-off baseline, not just its own cold pass
    assert shared["speedup_warm_vs_off"] > 1.0, r
    # compile-count guards: bounded by the bucket table, and the
    # batched decode step still compiles exactly once
    assert r["prefill_buckets"] == [16, 32, 64, 128]
    assert 1 <= r["restore_compiles"] <= len(r["prefill_buckets"])
    assert 1 <= r["prefill_compiles"] <= len(r["prefill_buckets"])
    assert r["decode_compiles"] == 1


@pytest.mark.slow   # ~33 s: block plumbing witnessed by
# test_serving_metrics_block; paged identity/capacity claims keep
# their tier-1 witnesses in test_serving_paged.py
def test_serving_paged_metrics_block():
    """The paged-KV-cache block (ISSUE 11): dense-vs-paged decode
    ms/token, warm shared-prompt admission via zero-copy block-table
    aliasing (with the dense copy-based speedup measured back to back
    as the PR-9 baseline), and concurrent-stream capacity at a fixed
    cache byte budget — the acceptance bar: >= 4x the dense layout.
    Exactness (streams identical across layouts and cache states) is
    asserted inside the block on every attempt; the zero-copy claim is
    pinned structurally — the restore and region-read programs never
    compile on the paged engine — and the compile guards ride along."""
    r = bench._serving_paged_metrics(
        streams=4, attempts=1, slots=4, decode_steps=12,
        cap_max_len=128, cap_dense_slots=2, cap_prompt_len=24,
        cap_new_tokens=4, cap_submitted=12)
    assert r["ok"] is True
    assert r["streams_identical"] is True
    d = r["decode"]
    assert d["ms_per_token_dense"] > 0.0
    assert d["ms_per_token_paged"] > 0.0
    assert d["paged_overhead_ratio"] > 0.0
    w = r["warm_admission"]
    for k in ("prefill_tokens_per_s_off", "prefill_tokens_per_s_cold",
              "prefill_tokens_per_s_warm"):
        assert w[k] > 0.0, k
    # a zero-copy hit must beat its own cold pass like the copy-based
    # path did (the PR-9 bar) — the full-size margin over the dense
    # baseline is measured at the defaults and recorded in PERF_NOTES
    assert w["speedup_warm_vs_cold"] >= 2.0, r
    # THE zero-copy dispatch witness: no restore program, no region
    # read ever compiled; the hits are visible as aliased blocks
    z = r["zero_copy"]
    assert z["restore_compiles"] == 0
    assert z["read_compiles"] == 0
    assert z["alias_blocks"] > 0
    # THE ISSUE-11 capacity bar: >= 4x concurrent streams in the same
    # cache bytes (peak measured over a real drain, both layouts
    # serving every request to completion)
    c = r["capacity"]
    assert c["peak_streams_dense"] == c["dense_max_streams"]
    assert c["capacity_ratio"] >= 4.0, r
    # compile guards: one decode program, prefill bounded by buckets
    assert r["decode_compiles"] == 1
    assert 1 <= r["prefill_compiles"] <= len(r["prefill_buckets"])


@pytest.mark.slow   # ~11 s: follows the spec/prefix/paged/tp block-test
# precedent — the SLO recorder/report surface stays witnessed by
# tests/test_serving_slo.py and the policy contrast by
# tests/test_serving_policy.py; block grading by bench_compare goldens
def test_serving_slo_metrics_block():
    """The request-level SLO block (ISSUE 12): a seeded bursty
    open-loop workload at ~1x and ~2x the measured sustainable load,
    per-request lifecycle records assembled off the event stream, and
    nearest-rank p50/p95/p99 TTFT / TPOT / queue-wait + goodput per
    load — with the workload's bit-reproducibility witnessed by its
    schedule fingerprint and the compile-count guards held (the
    recorder and load generator are pure host layers)."""
    r = bench._serving_slo_metrics(n_requests=10, prompt_len=24,
                                   new_tokens=6, slots=4, burst=2,
                                   max_len=64, prefill_len=32)
    assert r["ok"] is True
    assert r["sustainable_rps"] > 0.0
    assert r["deadline_s"] > 0.0
    assert set(r["loads"]) == {"1x", "2x"}
    fingerprints = set()
    for name, load in r["loads"].items():
        assert load["completed"] + load["shed"] <= 10
        assert load["completed"] >= 1
        for series in ("ttft_s", "tpot_s", "queue_wait_s"):
            s = load[series]
            assert s["n"] == load["completed"], (name, series)
            # nearest-rank percentiles are actual samples: ordered,
            # non-negative, p50 <= p95 <= p99
            assert 0.0 <= s["p50"] <= s["p95"] <= s["p99"], (name,
                                                             series)
        assert 0.0 <= load["goodput"] <= 1.0
        assert (load["deadline_misses"]
                == 10 - round(load["goodput"] * 10))
        # the exact samples and the Prometheus histogram quantiles are
        # computed over the SAME run (registry reset per load)
        assert load["crosscheck_aligned"] is True
        # same-seed rebuild equality is asserted INSIDE the block; the
        # fingerprint must also differ across loads (different periods)
        fingerprints.add(load["fingerprint"])
    assert len(fingerprints) == 2
    # compile guards: pure host layers — one decode program, prefill
    # bounded by the bucket table
    assert r["decode_compiles"] == 1
    assert 1 <= r["prefill_compiles"] <= len(r["prefill_buckets"])
    # the ISSUE-13 control-plane variant: FIFO vs policy on one
    # SLO-differentiated workload.  At this toy size the run is not
    # reliably overloaded, so the assertions are structural (the
    # direction story lives in the default-size PERF_NOTES round);
    # the compile identity IS asserted inside the block itself
    pol = r["policy"]
    hi_count = len([i for i in range(10) if i % 3 == 0])
    for variant in ("fifo", "policy"):
        v = pol[variant]
        assert 0.0 <= v["goodput"] <= 1.0, variant
        assert v["hp_ttft_p99_s"] >= 0.0
        assert v["hp_served"] == hi_count
        assert v["completed"] <= 10
    assert pol["fifo"]["preempted"] == pol["fifo"]["shed"] == 0
    assert pol["hp_ttft_p99_speedup"] > 0.0
    assert -1.0 <= pol["goodput_delta"] <= 1.0


@pytest.mark.slow   # ~25 s: block plumbing witnessed by
# test_serving_metrics_block; the reload/rollback/A-B correctness
# claims keep their tier-1 witnesses in test_serving_reload.py
def test_serving_reload_metrics_block():
    """The hot-reload block (ISSUE 16): swap pause as p99 step-time
    inflation of a mid-drain reload run over a steady run (back-to-back
    arrivals, so walls are compute), the per-phase reload wall split,
    zero dropped streams, the zero-recompile swap guard, and the
    shadow/A-B mirror cost at paced load with the saturated worst case
    recorded alongside."""
    r = bench._serving_reload_metrics(
        n_requests=8, new_tokens=6, burst=4, ab_period_s=0.4)
    assert r["ok"] is True
    # the reload wall is the sum of its phases, restore-dominated
    # (this reloader reads the checkpoint synchronously in the hook)
    assert r["restore_s"] > 0.0
    assert r["reload_wall_s"] >= r["restore_s"]
    assert abs(r["reload_wall_s"] - (r["restore_s"] + r["validate_s"]
                                     + r["swap_s"])) < 1e-3
    # swap pause is a max(0, delta): never negative, and the reload
    # run's p99 can't undercut it
    assert r["swap_pause_ms"] >= 0.0
    assert r["reload_step_ms_p99"] > 0.0
    assert r["steady_step_ms_p99"] > 0.0
    # THE robustness bars: no stream dropped, no program recompiled
    assert r["dropped_streams"] == 0
    assert r["completed"] == 8
    assert r["decode_compiles"] == 1
    ab = r["ab"]
    assert ab["mirrored_requests"] >= 1
    assert ab["mirror_shed"] == 0
    assert ab["ab_mirror_overhead_ratio"] > 0.0
    # sharing one host thread, mirrored work can only add wall —
    # the saturated ratio is the no-headroom ceiling
    assert ab["saturated_overhead_ratio"] > 0.0
    # restore-ahead contrast (ISSUE 17 satellite): the staged phases
    # were real work, the in-run swap alone paused the streams
    pf = r["prefetch"]
    assert pf["staged_restore_s"] > 0.0
    assert pf["swap_s"] >= 0.0
    assert pf["swap_pause_ms"] >= 0.0
    assert pf["dropped_streams"] == 0 and pf["completed"] == 8


@pytest.mark.slow   # ~40 s: three warmed replicas; the failover
# correctness claims keep their tier-1 witnesses in
# tests/test_serving_fleet.py — this pins the block's shape and bars
def test_serving_fleet_metrics_block():
    """The fleet block (ISSUE 17): unperturbed baseline vs a mid-drain
    replica kill with failover on (zero dropped streams, failover
    latency from the router's own resume events, no recompiles on the
    survivors) vs the same chaos with failover off (the goodput the
    machinery buys)."""
    r = bench._serving_fleet_metrics(n_requests=9, new_tokens=6)
    assert r["ok"] is True
    assert r["replicas"] == 3
    assert r["baseline_tokens_per_s"] > 0.0
    assert r["kill_tokens_per_s"] > 0.0
    assert r["throughput_vs_baseline"] > 0.0
    # THE robustness bars: every admitted stream served, failover
    # observed, nothing recompiled on the survivors
    assert r["dropped_streams"] == 0
    assert r["failovers"] >= 1
    assert r["failover_latency_s"] >= 0.0
    assert r["shed"] == 0
    assert r["decode_compiles"] == 3      # one warmed program each
    # what failover buys: identical chaos, strictly better goodput
    assert r["goodput_failover"] == 1.0
    assert r["goodput_no_failover"] < 1.0
    assert r["goodput_delta"] > 0.0
    assert r["victims_lost_no_failover"] >= 1


@pytest.mark.slow   # ~40 s: three warmed replicas; the rollout
# correctness claims keep their tier-1 witnesses in
# tests/test_serving_rollout.py — this pins the block's shape and bars
def test_serving_rollout_metrics_block():
    """The rolling-upgrade block (ISSUE 18): a gated rollout over a
    live 3-replica fleet promotes with zero dropped streams, a passing
    canary verdict, per-replica swap pauses, and no recompiles."""
    r = bench._serving_rollout_metrics(n_requests=12, new_tokens=5)
    assert r["ok"] is True
    assert r["replicas"] == 3
    # THE acceptance bars: promoted (asserted inside the helper),
    # nothing dropped, nothing halted or rolled back on the clean path
    assert r["dropped_streams"] == 0
    assert r["halts"] == 0
    assert r["rollbacks"] == 0
    assert r["shed"] == 0
    assert r["completed"] == 12
    # the operator-facing walls are real and ordered: the verdict
    # window sits inside the rollout wall
    assert r["rollout_wall_s"] > 0.0
    assert 0.0 < r["verdict_latency_s"] < r["rollout_wall_s"]
    # the reload pause is swap-only (prefetch staged the restore)
    assert 0.0 <= r["swap_pause_s_mean"] <= r["swap_pause_s_max"]
    assert r["swap_pause_s_max"] < 1.0
    # the canary arm really served pinned traffic in its window
    assert r["canary_offered"] >= 1
    assert r["canary_completed"] >= 1
    # one warmed program per replica, before and after the upgrade
    assert r["decode_compiles"] == 3


def test_serving_slo_block_reproducible_schedule():
    """Same seed ⇒ same arrival schedule and token-stream fingerprint,
    across two fresh builds of the workload (the bench block's
    bit-reproducibility acceptance, pinned without timing)."""
    from apex_tpu.serving import burst_arrivals, make_workload, \
        zero_overlap_prompts

    def build():
        prompts = zero_overlap_prompts(6, length=8, vocab=256, seed=7)
        return make_workload(prompts,
                             burst_arrivals(6, burst=2, period_s=0.5),
                             max_new_tokens=4, deadline_s=1.0, seed=7)

    assert (build().schedule_fingerprint()
            == build().schedule_fingerprint())


@pytest.mark.slow   # ~40 s: three warmed replicas, two chaos drains;
# the attribution/trace/alert correctness claims keep their tier-1
# witnesses in tests/test_obs_fleet.py — this pins the block's shape
def test_obs_fleet_metrics_block():
    """The fleet-observability-tax block (ISSUE 20): the serving_fleet
    chaos drain bare vs fully instrumented (named replicas + request
    recorder + per-step alert engine), standalone alert evaluation at
    n_rules/step, and the per-replica trace export."""
    r = bench._obs_fleet_metrics(n_requests=9, new_tokens=6, rounds=2,
                                 n_rules=8, n_alert_evals=50)
    assert r["ok"] is True
    assert r["bare_wall_s"] > 0.0
    assert r["instrumented_wall_s"] > 0.0
    # the 1.10x budget is the graded bar (bench_compare: "overhead" is
    # lower-is-better); the hard test bar only guards against the
    # instrumentation becoming the workload on a noisy CI host
    assert 0.0 < r["overhead_ratio"] < 3.0
    assert r["alert_eval_us_per_step"] > 0.0
    assert r["trace_export_ms"] > 0.0
    # replica_down fired when the kill dropped healthy below 3 and
    # never resolved (the bench run ends with the replica still dead)
    assert r["alerts_firing"] == 1
    assert r["alert_transitions"] == 1
    assert r["traced_requests"] == 9
    # one warmed program per replica on BOTH legs — attribution,
    # recording, and alerting added zero compiles
    assert r["decode_compiles"] == 3


def test_obs_metrics_block():
    """The observability-tax block (ISSUE 6 satellite): per-update cost
    of each instrument kind, span enter/exit, and exposition latency at
    1k series — the budget that proves instrumentation is negligible
    when no exporter is attached."""
    r = bench._obs_metrics(n=5_000, n_series=200)
    assert r["ok"] is True
    for k in ("counter_inc_ns", "gauge_set_ns", "histogram_observe_ns",
              "span_ns_no_recorder", "span_ns_recording",
              "exposition_ms"):
        assert r[k] > 0.0, k
    # a metric update is a lock + dict write; a no-recorder span is one
    # global read + a generator frame.  50 µs/op is ~100x the measured
    # cost — if these trip, instrumentation became the workload
    assert r["counter_inc_ns"] < 50_000.0
    assert r["gauge_set_ns"] < 50_000.0
    assert r["histogram_observe_ns"] < 50_000.0
    assert r["span_ns_no_recorder"] < 100_000.0
    assert r["exposition_series"] == 200


_SMOKE_BLOCK_FNS = (
    "_recovery_metrics", "_ckpt_async_metrics", "_supervisor_metrics",
    "_elastic_metrics", "_serving_metrics", "_serving_tp_metrics",
    "_serving_spec_metrics", "_serving_prefix_metrics",
    "_serving_paged_metrics", "_serving_slo_metrics", "_obs_metrics",
    "_obs_fleet_metrics")


@pytest.mark.slow   # ~62 s: the slim timing smoke has itself outgrown
# the tier-1 budget; the timing protocol stays guarded here in the slow
# lane and by every bench.py capture
def test_cpu_smoke_train_step_timing(monkeypatch):
    """The timing protocol on the real (CPU) backend, diagnostic blocks
    stubbed out: tier-1 keeps the real-execution train-step path (every
    block already has its own block test above), the full all-blocks
    smoke runs under -m slow.

    steps=16 + one retry: the t(2N) > 1.2*t(N) sanity gate is a
    real-execution check, not a precision claim, and 2-step timings on a
    loaded CI host can flake it.
    """
    for fn in _SMOKE_BLOCK_FNS:
        monkeypatch.setattr(bench, fn,
                            lambda *a, **k: {"ok": False,
                                             "skipped": "slim smoke"},
                            raising=True)
    for attempt in range(2):
        try:
            result = bench.run_config("cpu-smoke", steps=16)
            break
        except AssertionError:
            if attempt:
                raise
    assert result["value"] > 0
    assert result["config"]["loss_end"] < result["config"]["loss0"]
    for key in ("recovery", "serving", "serving_tp", "obs"):
        assert result[key] == {"ok": False, "skipped": "slim smoke"}


@pytest.mark.slow   # ~107 s: every diagnostic block over one real
                    # config — each block is tier-1-guarded by its own
                    # block test above; this is the glue run
def test_cpu_smoke_end_to_end(monkeypatch):
    """The real measurement path on the real (CPU) backend, every
    diagnostic block live."""
    for attempt in range(2):
        try:
            result = bench.run_config("cpu-smoke", steps=16)
            break
        except AssertionError:
            if attempt:
                raise
    assert result["value"] > 0
    assert result["config"]["loss_end"] < result["config"]["loss0"]
    # the diagnostic blocks ride every captured config
    assert result["recovery"]["ok"] is True
    assert result["ckpt_async"]["ok"] is True
    assert result["ckpt_async"]["bytes_identical"] is True
    assert result["supervisor"]["ok"] is True
    assert result["elastic"]["ok"] is True
    assert result["serving"]["ok"] is True
    # tp block: ok under the suite's forced 8 host devices; the
    # streams-identical witness is the acceptance bar riding along
    assert result["serving_tp"]["ok"] is True
    assert result["serving_tp"]["streams_identical"] is True
    assert result["serving_tp"]["tp1"]["decode_compiles"] == 1
    assert result["serving_tp"]["tp2"]["decode_compiles"] == 1
    assert result["serving_spec"]["ok"] is True
    assert result["serving_spec"]["streams_identical"] is True
    assert result["serving_prefix"]["ok"] is True
    assert result["serving_prefix"]["streams_identical"] is True
    assert result["serving_paged"]["ok"] is True
    assert result["serving_paged"]["streams_identical"] is True
    assert result["serving_slo"]["ok"] is True
    assert result["obs"]["ok"] is True
