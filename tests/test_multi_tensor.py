"""multi_tensor_apply + packing parity tests.

Mirrors tests/L0/run_amp/test_multi_tensor_scale.py,
test_multi_tensor_l2norm.py, test_multi_tensor_axpby.py in the reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)
from apex_tpu.utils import (
    flatten_dense_tensors,
    pack_pytree,
    unflatten_dense_tensors,
)


def _tree(rng, dtype=jnp.float32):
    return {
        "a": jnp.asarray(rng.standard_normal((37, 19)), dtype),
        "b": [jnp.asarray(rng.standard_normal((5,)), dtype)],
        "c": jnp.asarray(rng.standard_normal((128, 128)), dtype),
    }


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_multi_tensor_scale(rng, dtype):
    t = _tree(rng, dtype)
    out, found_inf = jax.jit(lambda x: multi_tensor_scale(x, 4.0))(t)
    ref = jax.tree.map(lambda x: x * jnp.asarray(4.0, dtype), t)
    for o, r in zip(jax.tree.leaves(out), jax.tree.leaves(ref)):
        np.testing.assert_allclose(np.asarray(o, np.float32), np.asarray(r, np.float32))
    assert not bool(found_inf)


def test_multi_tensor_scale_overflow(rng):
    t = _tree(rng)
    t["a"] = t["a"].at[0, 0].set(jnp.inf)
    _, found_inf = multi_tensor_scale(t, 0.5)
    assert bool(found_inf)
    t["a"] = t["a"].at[0, 0].set(jnp.nan)
    _, found_inf = multi_tensor_scale(t, 0.5)
    assert bool(found_inf)


def test_multi_tensor_axpby(rng):
    x, y = _tree(rng), _tree(rng)
    out, found_inf = multi_tensor_axpby(2.0, x, -3.0, y)
    ref = jax.tree.map(lambda a, b: 2.0 * a - 3.0 * b, x, y)
    for o, r in zip(jax.tree.leaves(out), jax.tree.leaves(ref)):
        np.testing.assert_allclose(o, r, rtol=1e-6)
    assert not bool(found_inf)


def test_multi_tensor_l2norm(rng):
    t = _tree(rng)
    total = multi_tensor_l2norm(t)
    flat = np.concatenate([np.ravel(l) for l in jax.tree.leaves(t)])
    np.testing.assert_allclose(float(total), np.linalg.norm(flat), rtol=1e-6)

    total2, per = multi_tensor_l2norm(t, per_tensor=True)
    np.testing.assert_allclose(float(total2), float(total))
    leaves = jax.tree.leaves(t)
    assert len(per) == len(leaves)
    for p, l in zip(per, leaves):
        np.testing.assert_allclose(float(p), np.linalg.norm(np.ravel(l)), rtol=1e-6)


def test_flatten_unflatten_roundtrip(rng):
    tensors = [
        jnp.asarray(rng.standard_normal((3, 4))),
        jnp.asarray(rng.standard_normal((7,))),
        jnp.asarray(rng.standard_normal((2, 2, 2))),
    ]
    flat = flatten_dense_tensors(tensors)
    assert flat.shape == (3 * 4 + 7 + 8,)
    back = unflatten_dense_tensors(flat, tensors)
    for a, b in zip(tensors, back):
        np.testing.assert_array_equal(a, b)


def test_pack_pytree_roundtrip(rng):
    t = _tree(rng)
    packed = pack_pytree(t)
    assert packed.flat.shape[0] % 1024 == 0
    back = packed.unpack()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), t, back)
