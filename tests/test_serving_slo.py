"""Request-level serving observability (ISSUE 12): lifecycle traces,
the deterministic open-loop load generator, and SLO reports.

THE acceptance run: a drained open-loop workload (bursty arrivals,
chunked prompts, prefix caching AND speculation enabled) whose
:class:`RequestTraceRecorder` output is *exactly reconciled* against
the scheduler's results and the raw event stream — every request one
complete span tree, phase durations summing to the total within the
recorder's stated rounding, prefix-hit/spec annotations matching the
events one for one.  Plus: the default-off identity (no recorder ⇒ no
new events, metric stream unchanged — snapshot-equal on a virtual
clock), deterministic virtual-clock timing (exact TTFT/TPOT arithmetic,
no sleeps), bit-reproducible workloads by seed, QueueFull shedding
charged against goodput, SLO percentile/crosscheck units, and the
instrumented-vs-bare scheduler step overhead bound (≤ 1.10x with a
recorder installed).
"""

import json
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging, obs
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs import request_trace as rt
from apex_tpu.obs import slo as oslo
from apex_tpu.obs.request_trace import PHASE_SUM_TOLERANCE_S

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=96)
MAX = 96
PREFILL = 16


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


@pytest.fixture(scope="module")
def engine(model, params):
    return sv.DecodeEngine(model, params, slots=4, max_len=MAX,
                           prefill_len=PREFILL)


@pytest.fixture()
def capture_events():
    """Append every emitted event dict to a list for the duration."""
    seen = []
    _logging.add_event_sink(seen.append)
    yield seen
    _logging.remove_event_sink(seen.append)


def _sched(engine, clock, **kw):
    return sv.ContinuousBatchingScheduler(engine, log_interval=10 ** 9,
                                          clock=clock, **kw)


# ---------------------------------------------------------------------------
# loadgen units: arrival processes, prompt mixes, workload validation
# ---------------------------------------------------------------------------

class TestLoadgenUnits:
    def test_uniform_arrivals(self):
        assert sv.uniform_arrivals(4, 2.0) == (0.0, 0.5, 1.0, 1.5)
        with pytest.raises(ValueError):
            sv.uniform_arrivals(0, 1.0)
        with pytest.raises(ValueError):
            sv.uniform_arrivals(4, 0.0)

    def test_poisson_arrivals_seeded(self):
        a = sv.poisson_arrivals(16, 5.0, seed=3)
        b = sv.poisson_arrivals(16, 5.0, seed=3)
        c = sv.poisson_arrivals(16, 5.0, seed=4)
        assert a == b                      # bit-identical by seed
        assert a != c
        assert a[0] == 0.0
        assert all(y >= x for x, y in zip(a, a[1:]))

    def test_burst_arrivals_trains(self):
        a = sv.burst_arrivals(6, burst=2, period_s=1.0)
        assert a == (0.0, 0.0, 1.0, 1.0, 2.0, 2.0)
        spaced = sv.burst_arrivals(4, burst=2, period_s=1.0,
                                   spacing_s=0.25)
        assert spaced == (0.0, 0.25, 1.0, 1.25)
        with pytest.raises(ValueError):       # burst outlasts period
            sv.burst_arrivals(4, burst=3, period_s=1.0, spacing_s=0.5)

    def test_prompt_mixes_seeded_and_shaped(self):
        sp = sv.shared_prefix_prompts(4, shared_len=8, suffix_len=3,
                                      vocab=128, seed=1)
        assert all(p[:8] == sp[0][:8] for p in sp)
        assert len({tuple(p) for p in sp}) == 4       # unique suffixes
        assert sp == sv.shared_prefix_prompts(4, shared_len=8,
                                              suffix_len=3, vocab=128,
                                              seed=1)
        zo = sv.zero_overlap_prompts(3, length=6, vocab=128, seed=2)
        assert all(len(p) == 6 for p in zo)
        ml = sv.mixed_length_prompts(8, prefill_len=64, vocab=128)
        assert [len(p) for p in ml] == [
            max(1, int(64 * f)) for f in sv.loadgen.LENGTH_SKEW_FRACTIONS]

    def test_workload_validation(self):
        reqs = (sv.Request("a", [1], 2), sv.Request("b", [1], 2))
        with pytest.raises(ValueError, match="mismatch"):
            sv.OpenLoopWorkload(reqs, (0.0,), (None, None))
        with pytest.raises(ValueError, match="non-decreasing"):
            sv.OpenLoopWorkload(reqs, (1.0, 0.5), (None, None))
        with pytest.raises(ValueError, match="< 0"):
            sv.OpenLoopWorkload(reqs, (-1.0, 0.5), (None, None))
        with pytest.raises(ValueError, match="positive"):
            sv.OpenLoopWorkload(reqs, (0.0, 1.0), (0.0, None))
        dup = (sv.Request("a", [1], 2), sv.Request("a", [1], 2))
        with pytest.raises(ValueError, match="duplicate"):
            sv.OpenLoopWorkload(dup, (0.0, 1.0), (None, None))
        with pytest.raises(ValueError, match="prompts vs"):
            sv.make_workload([[1], [2]], (0.0,), max_new_tokens=1)

    def test_fingerprint_covers_schedule_and_streams(self):
        wl = sv.make_workload([[1, 2], [3, 4]], (0.0, 1.0),
                              max_new_tokens=4, deadline_s=2.0)
        same = sv.make_workload([[1, 2], [3, 4]], (0.0, 1.0),
                                max_new_tokens=4, deadline_s=2.0)
        assert wl.schedule_fingerprint() == same.schedule_fingerprint()
        for other in (
                sv.make_workload([[1, 2], [3, 5]], (0.0, 1.0),
                                 max_new_tokens=4, deadline_s=2.0),
                sv.make_workload([[1, 2], [3, 4]], (0.0, 1.5),
                                 max_new_tokens=4, deadline_s=2.0),
                sv.make_workload([[1, 2], [3, 4]], (0.0, 1.0),
                                 max_new_tokens=5, deadline_s=2.0)):
            assert wl.schedule_fingerprint() != other.schedule_fingerprint()
        assert wl.offered_rps == 1.0

    def test_generator_guards(self, engine):
        wl = sv.make_workload([[1, 2, 3]], (0.0,), max_new_tokens=2)
        sched = _sched(engine, time.monotonic)
        with pytest.raises(ValueError, match="advanceable"):
            sv.LoadGenerator(sched, wl, step_time_s=0.25)
        with pytest.raises(ValueError, match="step_time_s"):
            sv.LoadGenerator(_sched(engine, sv.VirtualClock()), wl,
                             step_time_s=0.0)
        # a virtual clock that never advances + a pending future
        # arrival must fail loudly instead of spinning forever
        future = sv.make_workload([[1, 2], [3, 4]], (0.0, 10.0),
                                  max_new_tokens=1)
        gen = sv.LoadGenerator(_sched(engine, sv.VirtualClock()), future)
        with pytest.raises(RuntimeError, match="did not advance"):
            gen.run()

    def test_virtual_clock(self):
        clk = sv.VirtualClock(1.0)
        assert clk() == 1.0
        assert clk.advance(0.25) == 1.25
        with pytest.raises(ValueError):
            clk.advance(-0.1)


# ---------------------------------------------------------------------------
# deterministic virtual-clock timing
# ---------------------------------------------------------------------------

class TestVirtualClockTiming:
    def test_exact_latency_arithmetic(self, engine):
        """On a shared VirtualClock every latency is an exact multiple
        of the virtual step: a one-chunk prompt admits, prefills,
        samples its first token AND rides the same step's decode
        (2 tokens inside step 1, TTFT exactly 0.0), then one token per
        step — 3 tokens finish one step later (total exactly 0.25,
        TPOT exactly 0.125)."""
        clk = sv.VirtualClock()
        sched = _sched(engine, clk)
        rec = rt.RequestTraceRecorder(clock=clk).install()
        try:
            wl = sv.make_workload([[5, 6, 7, 8]], (0.0,),
                                  max_new_tokens=3, deadline_s=10.0)
            out = sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        finally:
            rec.uninstall()
        res = out.results["lg0"]
        assert res.ttft_s == 0.0
        assert res.total_s == 0.25
        (record,) = rec.records()
        assert record.complete
        assert record.queue_wait_s == 0.0
        assert record.prefill_s == 0.0
        assert record.decode_s == 0.25
        assert record.total_s == 0.25
        assert record.tpot_s == 0.125
        # the recorder's view and the scheduler's event measurements
        # agree exactly — one shared clock, one timeline
        assert record.scheduler_ttft_s == res.ttft_s
        assert record.scheduler_queue_wait_s == 0.0
        assert out.goodput == 1.0 and out.duration_s == 0.5

    def test_chunked_prompt_ttft_spans_steps(self, engine):
        """A prompt needing two budgeted chunks takes two steps to
        first token: TTFT is exactly one virtual step."""
        clk = sv.VirtualClock()
        sched = _sched(engine, clk, prefill_budget=4)
        wl = sv.make_workload([[1] * 8], (0.0,), max_new_tokens=1)
        out = sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        assert out.results["lg0"].ttft_s == 0.25

    def test_token_streams_reproducible_by_seed(self, engine):
        """Same seed ⇒ same workload ⇒ same token streams, run to run
        (fresh scheduler each time, arrival timing irrelevant)."""
        def one_run(step_time):
            clk = sv.VirtualClock()
            sched = _sched(engine, clk)
            prompts = sv.zero_overlap_prompts(6, length=7, vocab=128,
                                              seed=11)
            wl = sv.make_workload(
                prompts, sv.poisson_arrivals(6, 4.0, seed=11),
                max_new_tokens=4, temperature=0.8, top_k=8, seed=11)
            out = sv.LoadGenerator(sched, wl, step_time_s=step_time).run()
            return (wl.schedule_fingerprint(),
                    {r: res.tokens for r, res in out.results.items()})

        fp_a, tokens_a = one_run(0.25)
        fp_b, tokens_b = one_run(0.25)
        assert fp_a == fp_b
        assert tokens_a == tokens_b
        # arrival *timing* is scheduling, not numerics: a different
        # virtual step cost reorders nothing in any stream
        _, tokens_c = one_run(0.125)
        assert tokens_c == tokens_a


# ---------------------------------------------------------------------------
# THE acceptance run: recorder output exactly reconciled
# ---------------------------------------------------------------------------

class TestReconciliation:
    @pytest.fixture(scope="class")
    def drained(self, model, params):
        """A drained bursty open-loop run with prefix caching AND
        speculation on, chunked prompts, and a queueing second burst —
        returns (scheduler, loadgen result, recorder, raw events).
        Class-scoped: ONE run (and one engine's worth of compiles)
        feeds every reconciliation assertion below, all of which only
        read it."""
        events = []
        _logging.add_event_sink(events.append)
        eng = sv.DecodeEngine(model, params, slots=4, max_len=MAX,
                              prefill_len=PREFILL)
        clk = sv.VirtualClock()
        sched = _sched(
            eng, clk,
            speculation=sv.SpeculationConfig(max_draft=2),
            prefix_caching=sv.PrefixCacheConfig(max_tokens=1 << 14))
        # 8 requests sharing a 32-token prefix (2 cache blocks), unique
        # 4-token tails; prompts chunk (36 > prefill_len=16); two
        # bursts of 4 so the second burst queues behind busy slots
        prompts = sv.shared_prefix_prompts(8, shared_len=32,
                                           suffix_len=4, vocab=128,
                                           seed=5)
        wl = sv.make_workload(
            prompts, sv.burst_arrivals(8, burst=4, period_s=0.5),
            max_new_tokens=6, deadline_s=64.0, seed=5)
        rec = rt.RequestTraceRecorder(clock=clk).install()
        try:
            out = sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        finally:
            rec.uninstall()
            _logging.remove_event_sink(events.append)
        yield sched, out, rec, events
        sched.close()

    def test_every_request_one_complete_record(self, drained):
        sched, out, rec, _ = drained
        records = rec.records()
        assert out.completed == out.offered == 8
        assert {r.rid for r in records} == set(out.results)
        assert len(records) == 8 and not rec.open_records()
        for record in records:
            assert record.complete
            res = out.results[record.rid]
            assert record.new_tokens == len(res.tokens)
            assert record.prompt_tokens == 36
            assert record.finish_reason == res.finish_reason
            assert record.slot is not None

    def test_phase_durations_sum_to_total(self, drained):
        _, out, rec, _ = drained
        for record in rec.records():
            total = (record.queue_wait_s + record.prefill_s
                     + record.decode_s)
            assert abs(total - record.total_s) <= PHASE_SUM_TOLERANCE_S
            # recorder timeline == scheduler timeline (shared clock)
            res = out.results[record.rid]
            assert record.ttft_s == pytest.approx(res.ttft_s, abs=1e-6)
            assert record.total_s == pytest.approx(res.total_s, abs=1e-6)
        # the second burst queued behind busy slots: somebody waited
        assert any(r.queue_wait_s > 0 for r in rec.records())

    def test_chunks_cover_the_uncached_prompt(self, drained):
        _, _, rec, _ = drained
        for record in rec.records():
            saved = (record.prefix or {}).get("saved_tokens") or 0
            assert (sum(c["chunk_tokens"] for c in record.chunks)
                    + saved == record.prompt_tokens)
            offs = [c["offset_tokens"] for c in record.chunks]
            assert offs == sorted(offs)
            if record.chunks:
                assert record.chunks[0]["offset_tokens"] == saved

    def test_prefix_annotations_match_event_stream(self, drained):
        _, _, rec, events = drained
        hits = {e["rid"]: e for e in events
                if e["event"] == "serving_prefix_hit"}
        misses = {e["rid"] for e in events
                  if e["event"] == "serving_prefix_miss"}
        assert hits and misses            # cold first burst, warm later
        for record in rec.records():
            if record.rid in hits:
                assert record.prefix["hit"] is True
                assert (record.prefix["saved_tokens"]
                        == hits[record.rid]["saved_tokens"])
            elif record.rid in misses:
                assert record.prefix == {"hit": False}

    def test_spec_annotations_match_event_stream(self, drained):
        sched, _, rec, events = drained
        per_rid = {}
        for e in events:
            if e["event"] == "serving_spec_verify":
                st = per_rid.setdefault(e["rid"], {"dispatches": 0,
                                                   "drafted": 0,
                                                   "accepted": 0,
                                                   "emitted": 0})
                st["dispatches"] += 1
                for f in ("drafted", "accepted", "emitted"):
                    st[f] += e[f]
        for record in rec.records():
            got = {k: record.spec.get(k, 0)
                   for k in ("dispatches", "drafted", "accepted",
                             "emitted")}
            want = per_rid.get(record.rid, {"dispatches": 0,
                                            "drafted": 0, "accepted": 0,
                                            "emitted": 0})
            assert got == want
        # and the totals reconcile against the scheduler's own books
        stats = sched.spec_stats
        records = rec.records()
        for key in ("dispatches", "drafted", "accepted", "emitted"):
            assert sum(r.spec.get(key, 0) for r in records) == stats[key]

    def test_chrome_trace_one_track_per_request(self, drained, tmp_path):
        _, _, rec, _ = drained
        payload = rec.export(str(tmp_path / "req.trace.json"))
        loaded = json.loads((tmp_path / "req.trace.json").read_text())
        assert loaded == payload
        events = loaded["traceEvents"]
        names = {e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert names == {r.rid for r in rec.records()}
        by_tid = {}
        for e in events:
            if e.get("ph") == "X":
                by_tid.setdefault(e["tid"], []).append(e)
        assert len(by_tid) == 8           # one track per request
        for tid, slices in by_tid.items():
            by_name = {e["name"]: e for e in slices}
            req = by_name["request"]
            # a complete span tree: every phase/chunk slice contained
            # within its request slice on the same track
            for e in slices:
                assert e["ts"] >= req["ts"] - 1e-6
                assert (e["ts"] + e["dur"]
                        <= req["ts"] + req["dur"] + 1e-6)
            assert {"queued", "prefill", "decode"} <= set(by_name)

    def test_jsonl_export_round_trips(self, drained, tmp_path):
        _, _, rec, _ = drained
        path = tmp_path / "req.jsonl"
        n = rec.export_jsonl(str(path))
        rows = [json.loads(line) for line in
                path.read_text().splitlines()]
        assert n == len(rows) == 8
        assert ({r["rid"] for r in rows}
                == {r.rid for r in rec.records()})
        for row, record in zip(rows, rec.records()):
            assert row["total_s"] == record.total_s

    def test_slo_report_over_the_run(self, drained):
        _, out, rec, _ = drained
        report = oslo.build_report(rec.records(), offered=out.offered,
                                   deadlines=out.deadlines,
                                   arrivals=out.arrivals,
                                   duration_s=out.duration_s)
        assert report.completed == 8 and report.incomplete == 0
        assert report.goodput == out.goodput == 1.0
        ttft = sorted(r.ttft_s for r in rec.records())
        assert report.ttft["p50"] == ttft[math.ceil(0.5 * 8) - 1]
        assert report.ttft["p99"] == ttft[-1]
        d = report.to_dict()
        assert d["goodput"] == 1.0
        assert d["ttft_s"]["n"] == 8


# ---------------------------------------------------------------------------
# default-off identity + overhead bound
# ---------------------------------------------------------------------------

def _serving_metric_state():
    """The serving-relevant slice of the default registry snapshot."""
    snap = obs.snapshot()
    return {name: entry for name, entry in snap.items()
            if name.startswith("apex_serving_")
            or name == "apex_events_total"}


class TestDefaultOffIdentity:
    def test_no_recorder_no_new_events_same_metrics(self, engine):
        """Recorder on vs off: the event stream (kinds + rids, in
        order) and the metric stream are IDENTICAL — the recorder is a
        pure consumer.  Virtual clock ⇒ even histogram sums match
        exactly."""
        def one_run(install_recorder):
            clk = sv.VirtualClock()
            sched = _sched(engine, clk)
            prompts = sv.zero_overlap_prompts(5, length=6, vocab=128,
                                              seed=9)
            wl = sv.make_workload(
                prompts, sv.burst_arrivals(5, burst=2, period_s=1.0),
                max_new_tokens=3, seed=9)
            seen = []
            _logging.add_event_sink(seen.append)
            rec = (rt.RequestTraceRecorder(clock=clk).install()
                   if install_recorder else None)
            obs.metrics.reset()
            try:
                sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
            finally:
                if rec is not None:
                    rec.uninstall()
                _logging.remove_event_sink(seen.append)
            stream = [(e["event"], e.get("rid")) for e in seen]
            return stream, _serving_metric_state()

        stream_off, metrics_off = one_run(False)
        stream_on, metrics_on = one_run(True)
        assert stream_on == stream_off     # no new events, none missing
        assert metrics_on == metrics_off   # metric stream unchanged

    def test_queue_wait_histogram_fed(self, engine):
        before = obs.bridge.SERVING_QUEUE_WAIT.count()
        clk = sv.VirtualClock()
        sched = _sched(engine, clk)
        wl = sv.make_workload([[1, 2, 3]], (0.0,), max_new_tokens=1)
        sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        assert obs.bridge.SERVING_QUEUE_WAIT.count() == before + 1

    def test_goodput_gauge_only_with_deadlines(self, engine):
        obs.bridge.SERVING_GOODPUT.set(-1.0)       # sentinel
        clk = sv.VirtualClock()
        wl = sv.make_workload([[1, 2, 3]], (0.0,), max_new_tokens=1)
        sv.LoadGenerator(_sched(engine, clk), wl,
                         step_time_s=0.25).run()
        assert obs.bridge.SERVING_GOODPUT.value() == -1.0   # untouched
        clk = sv.VirtualClock()
        wl = sv.make_workload([[1, 2, 3]], (0.0,), max_new_tokens=1,
                              deadline_s=10.0)
        out = sv.LoadGenerator(_sched(engine, clk), wl,
                               step_time_s=0.25).run()
        assert out.goodput == 1.0
        assert obs.bridge.SERVING_GOODPUT.value() == 1.0


class TestDeadlineFromArrival:
    def test_submit_lag_never_extends_a_deadline(self, engine):
        """A request due MID-step is submitted at the next boundary —
        the submit lag must come out of its deadline budget, not
        silently extend it.  Arrival at t=0.1, submitted at t=0.25,
        finished at t=0.5: submit-relative elapsed is 0.25 (under a
        0.3 deadline) but arrival-relative is 0.4 — a miss."""
        clk = sv.VirtualClock()
        sched = _sched(engine, clk)
        rec = rt.RequestTraceRecorder(clock=clk).install()
        try:
            wl = sv.make_workload([[1, 2, 3]], (0.1,),
                                  max_new_tokens=3, deadline_s=0.3)
            out = sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        finally:
            rec.uninstall()
        res = out.results["lg0"]
        assert out.arrivals["lg0"] == 0.1
        assert res.total_s == 0.25           # submit-relative: "meets"
        assert out.met_deadline["lg0"] is False
        assert out.goodput == 0.0
        # the report agrees when given the arrivals, and documents the
        # submission-relative fallback when not
        report = oslo.build_report(rec.records(), offered=1,
                                   deadlines=out.deadlines,
                                   arrivals=out.arrivals)
        assert report.goodput == 0.0 and report.deadline_misses == 1
        fallback = oslo.build_report(rec.records(), offered=1,
                                     deadlines=out.deadlines)
        assert fallback.goodput == 1.0


class TestShedding:
    def test_queue_full_sheds_and_charges_goodput(self, model, params):
        """Open-loop: a simultaneous burst past queue + slot capacity
        sheds the overflow (never retried) and goodput counts the shed
        arrivals against the offered total."""
        eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                              prefill_len=PREFILL)
        clk = sv.VirtualClock()
        sched = _sched(eng, clk, max_queue=2)
        prompts = sv.zero_overlap_prompts(5, length=4, vocab=128,
                                          seed=4)
        wl = sv.make_workload(prompts, (0.0,) * 5, max_new_tokens=2,
                              deadline_s=100.0)
        out = sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        # all 5 arrive before the first step boundary, so only the
        # 2-deep bounded queue accepts — the other 3 shed immediately
        assert len(out.rejected) == 3
        assert out.completed == 2
        assert out.goodput == 2 / 5
        assert [r for r in out.met_deadline.values()].count(True) == 2
        report = oslo.build_report(
            [], offered=out.offered, deadlines=out.deadlines)
        assert report.goodput == 0.0      # no records at all -> 0 met


class TestOverheadBound:
    def test_recorder_overhead_within_1_10x(self, engine):
        """The acceptance bound: a drained event-rich workload with a
        recorder installed costs <= 1.10x the bare drain (the recorder
        is dict bookkeeping per event against a decode dispatch per
        step).  Best-of-3 interleaved attempts absorb scheduler noise."""
        prompts = sv.zero_overlap_prompts(24, length=5, vocab=128,
                                          seed=13)

        def drain(with_recorder):
            sched = sv.ContinuousBatchingScheduler(engine,
                                                   log_interval=10 ** 9)
            wl = sv.make_workload(prompts, (0.0,) * len(prompts),
                                  max_new_tokens=2, seed=13)
            rec = (rt.RequestTraceRecorder().install()
                   if with_recorder else None)
            try:
                t0 = time.perf_counter()
                sv.LoadGenerator(sched, wl).run()
                return time.perf_counter() - t0
            finally:
                if rec is not None:
                    rec.uninstall()

        drain(True)                        # warm compiles outside timing
        # one retry: the bound is a tight 1.10x on a wall-clock drain,
        # and a loaded CI host can hand either side one unlucky run —
        # best-of-3 per side per attempt absorbs most of it
        for attempt in range(2):
            bare = min(drain(False) for _ in range(3))
            instrumented = min(drain(True) for _ in range(3))
            if instrumented <= 1.10 * bare:
                break
        assert instrumented <= 1.10 * bare, (
            f"recorder-instrumented drain {instrumented:.4f}s vs bare "
            f"{bare:.4f}s = {instrumented / bare:.3f}x > 1.10x")


# ---------------------------------------------------------------------------
# recorder units
# ---------------------------------------------------------------------------

class TestRecorderUnits:
    def test_bounded_and_counts_drops(self):
        rec = rt.RequestTraceRecorder(max_requests=2)
        rec.install()
        try:
            for i in range(4):
                # queued AND admitted both hit the create path — a
                # refused request must count as ONE drop, not one per
                # lifecycle event that retried the create
                _logging.emit_event("serving_request_queued",
                                    rid=f"r{i}", prompt_tokens=1)
                _logging.emit_event("serving_request_admitted",
                                    rid=f"r{i}", slot=0)
        finally:
            rec.uninstall()
        assert len(rec.open_records()) == 2
        assert rec.dropped == 2
        trace = rec.to_chrome_trace()
        assert trace["otherData"]["dropped_requests"] == 2
        assert trace["otherData"]["open_requests"] == 2

    def test_stray_events_do_not_fabricate_records(self):
        rec = rt.RequestTraceRecorder()
        rec.install()
        try:
            _logging.emit_event("serving_request_finished", rid="ghost",
                                new_tokens=3)
            _logging.emit_event("serving_prefill_chunk", rid="ghost",
                                bucket=16, chunk_tokens=16)
            _logging.emit_event("serving_step", step=1)   # no rid
            _logging.emit_event("checkpoint_saved", step=1)
        finally:
            rec.uninstall()
        assert not rec.records() and not rec.open_records()

    def test_context_manager_and_validation(self):
        with pytest.raises(ValueError):
            rt.RequestTraceRecorder(max_requests=0)
        with rt.recording_requests() as rec:
            assert rec.installed()
            _logging.emit_event("serving_request_queued", rid="x",
                                prompt_tokens=2)
        assert not rec.installed()
        assert len(rec.open_records()) == 1

    def test_install_idempotent(self):
        rec = rt.RequestTraceRecorder()
        rec.install()
        rec.install()
        try:
            _logging.emit_event("serving_request_queued", rid="once",
                                prompt_tokens=1)
        finally:
            rec.uninstall()
        assert len(rec.open_records()) == 1


# ---------------------------------------------------------------------------
# SLO units: percentiles, report shape, crosscheck
# ---------------------------------------------------------------------------

class TestSLOUnits:
    def test_percentile_nearest_rank(self):
        xs = [10.0, 20.0, 30.0, 40.0]
        assert oslo.percentile(xs, 0.0) == 10.0
        assert oslo.percentile(xs, 0.25) == 10.0
        assert oslo.percentile(xs, 0.5) == 20.0
        assert oslo.percentile(xs, 0.51) == 30.0
        assert oslo.percentile(xs, 0.99) == 40.0
        assert oslo.percentile(xs, 1.0) == 40.0
        assert oslo.percentile([7.0], 0.99) == 7.0
        assert math.isnan(oslo.percentile([], 0.5))
        for bad in (-0.1, 1.1, float("nan"), float("inf")):
            with pytest.raises(ValueError):
                oslo.percentile([1.0], bad)

    def test_summarize_empty(self):
        s = oslo.summarize([])
        assert s["n"] == 0
        assert all(math.isnan(s[k]) for k in ("mean", "p50", "p99"))

    def test_build_report_guards(self):
        with pytest.raises(ValueError, match="undercount"):
            oslo.build_report(
                [rt.RequestRecord(rid="a", t_queued=0.0, t_admitted=0.0,
                                  t_first=0.0, t_finished=1.0)],
                offered=0)
        with pytest.raises(ValueError, match="unknown crosscheck"):
            oslo.build_report([], histograms={"bogus": None})

    def test_goodput_none_without_deadlines(self):
        rec = rt.RequestRecord(rid="a", new_tokens=2, t_queued=0.0,
                               t_admitted=0.0, t_first=0.5,
                               t_finished=1.0)
        report = oslo.build_report([rec], deadlines={"a": None})
        assert report.goodput is None
        report = oslo.build_report([rec], deadlines={"a": 0.75})
        assert report.goodput == 0.0 and report.deadline_misses == 1
        report = oslo.build_report([rec], deadlines={"a": 2.0})
        assert report.goodput == 1.0

    def test_crosscheck_agreement(self):
        h = obs.Histogram("apex_unit_xc_seconds",
                          buckets=(0.1, 1.0, 10.0))
        samples = [0.05, 0.5, 0.5, 5.0]
        for v in samples:
            h.observe(v)
        out = oslo.crosscheck_quantiles(samples, h)
        assert out["aligned"]
        for q in ("p50", "p95", "p99"):
            assert out["quantiles"][q]["agree"], (q, out)
        # overflow clamp counts as agreement for an overflow sample
        h2 = obs.Histogram("apex_unit_xc2_seconds", buckets=(1.0,))
        h2.observe(5.0)
        out2 = oslo.crosscheck_quantiles([5.0], h2)
        assert out2["quantiles"]["p99"]["estimate"] == 1.0
        assert out2["quantiles"]["p99"]["agree"]
        # misaligned counts are reported, not hidden
        h.observe(0.5)
        assert not oslo.crosscheck_quantiles(samples, h)["aligned"]

    def test_report_dict_deterministic(self):
        recs = [rt.RequestRecord(rid=f"r{i}", new_tokens=3,
                                 t_queued=0.0, t_admitted=0.25 * i,
                                 t_first=0.25 * i + 0.25,
                                 t_finished=0.25 * i + 0.75)
                for i in range(4)]
        a = oslo.build_report(recs, duration_s=2.0).to_dict()
        b = oslo.build_report(recs, duration_s=2.0).to_dict()
        assert a == b
        assert a["tpot_s"]["p50"] == 0.25
        assert a["throughput_rps"] == 2.0
        assert a["output_tokens"] == 12
