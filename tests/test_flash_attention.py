"""Flash attention parity tests (mirrors apex/contrib/test/fmha and
multihead_attn numeric-parity style): the Pallas kernel (interpret mode on
CPU) must match the materialized jnp reference for values and gradients,
across causal/padding/varlen/cross-attention cases and dtypes.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import flash_attention, mha_reference


@pytest.fixture(autouse=True)
def _interpret_kernels(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "interpret")
    yield


def _rand_qkv(rng, b, h, sq, sk, d, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((b, h, sq, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, h, sk, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, h, sk, d)), dtype)
    return q, k, v


def _check(q, k, v, rng, causal=False, segment_ids=None, rtol=2e-5,
           atol=2e-5, block=64):
    out = flash_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                          block_q=block, block_k=block)
    qseg, kseg = ((segment_ids, segment_ids)
                  if segment_ids is not None and not isinstance(segment_ids, tuple)
                  else (segment_ids or (None, None)))
    ref = mha_reference(q, k, v, causal=causal, q_segment_ids=qseg,
                        kv_segment_ids=kseg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)

    # gradient parity: scalar loss, all three inputs
    do = jnp.asarray(rng.standard_normal(out.shape), out.dtype)

    def f_flash(q, k, v):
        y = flash_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                            block_q=block, block_k=block)
        return jnp.sum(y.astype(jnp.float32) * do.astype(jnp.float32))

    def f_ref(q, k, v):
        y = mha_reference(q, k, v, causal=causal, q_segment_ids=qseg,
                          kv_segment_ids=kseg)
        return jnp.sum(y.astype(jnp.float32) * do.astype(jnp.float32))

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=rtol * 5, atol=atol * 5)


def test_plain_attention(rng):
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64)
    _check(q, k, v, rng)


def test_causal(rng):
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64)
    _check(q, k, v, rng, causal=True)


def test_multiblock_causal(rng):
    """More k/v blocks than q blocks exercises the online-softmax rescale."""
    q, k, v = _rand_qkv(rng, 1, 1, 256, 256, 64)
    _check(q, k, v, rng, causal=True, block=64)


def test_padding_mask_via_segment_ids(rng):
    """Key padding = segment id 0 on pads; matches reference semantics."""
    b, h, s, d = 2, 2, 128, 64
    q, k, v = _rand_qkv(rng, b, h, s, s, d)
    seg = jnp.ones((b, s), jnp.int32).at[:, 96:].set(0)
    # queries in the pad region are fully masked against the live region
    out = flash_attention(q, k, v, segment_ids=seg, block_q=64, block_k=64)
    ref = mha_reference(q, k, v, q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_varlen_packing(rng):
    """Two packed sequences per row (THD layout, fmha parity): tokens only
    attend within their own segment."""
    b, h, s, d = 1, 2, 128, 64
    q, k, v = _rand_qkv(rng, b, h, s, s, d)
    seg = jnp.concatenate([jnp.full((b, 64), 1, jnp.int32),
                           jnp.full((b, 64), 2, jnp.int32)], axis=1)
    _check(q, k, v, rng, causal=True, segment_ids=seg)
    # cross-segment leakage check: perturb segment 2, segment 1 unchanged
    out1 = flash_attention(q, k, v, causal=True, segment_ids=seg,
                           block_q=64, block_k=64)
    k2 = k.at[:, :, 64:].add(1.0)
    v2 = v.at[:, :, 64:].add(1.0)
    out2 = flash_attention(q, k2, v2, causal=True, segment_ids=seg,
                           block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out1[:, :, :64]),
                               np.asarray(out2[:, :, :64]),
                               rtol=1e-6, atol=1e-6)


def test_cross_attention_lengths(rng):
    q, k, v = _rand_qkv(rng, 1, 2, 64, 128, 64)
    _check(q, k, v, rng)


def test_causal_more_queries_than_keys(rng):
    """causal sq > sk: the leading sq-sk query rows see NO keys and must
    emit exact zeros with zero gradients (regression: the square-causal
    fast path skipped the row zeroing)."""
    q, k, v = _rand_qkv(rng, 1, 2, 128, 64, 64)
    _check(q, k, v, rng, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_array_equal(np.asarray(out[:, :, :64]), 0.0)


def test_bf16(rng):
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_fully_masked_rows_zero(rng):
    """Rows with no visible key emit exactly 0 with 0 gradient (fused-softmax
    convention)."""
    b, h, s, d = 1, 1, 128, 64
    q, k, v = _rand_qkv(rng, b, h, s, s, d)
    # all keys in segment 9; queries in segment 1 → no q sees any k
    qseg = jnp.ones((b, s), jnp.int32)
    kseg = jnp.full((b, s), 9, jnp.int32)
    out = flash_attention(q, k, v, segment_ids=(qseg, kseg),
                          block_q=64, block_k=64)
    np.testing.assert_array_equal(np.asarray(out), 0.0)
    g = jax.grad(lambda q: flash_attention(
        q, k, v, segment_ids=(qseg, kseg), block_q=64, block_k=64
    ).sum())(q)
    np.testing.assert_array_equal(np.asarray(g), 0.0)


def test_fallback_path_matches(rng):
    """Shapes the kernel rejects (d=32) route to jnp with same semantics."""
    q, k, v = _rand_qkv(rng, 1, 2, 48, 48, 32)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
