"""Tensor-parallel layer/mapping tests on the forced 8-device CPU mesh.

Mirrors tests/L0/run_transformer: test_mapping.py, test_layers.py,
test_cross_entropy.py — numeric parity of the sharded path against a
single-device dense reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    vocab_parallel_cross_entropy,
)


@pytest.fixture
def tp4_mesh(devices):
    mesh = parallel_state.initialize_model_parallel(4, 1, devices=devices[:4])
    yield mesh
    parallel_state.destroy_model_parallel()


def _smap(f, mesh, in_specs, out_specs):
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **NO_REP_CHECK)


def test_parallel_state_shapes(tp4_mesh):
    assert parallel_state.get_tensor_model_parallel_world_size() == 4
    assert parallel_state.get_pipeline_model_parallel_world_size() == 1
    assert parallel_state.get_data_parallel_world_size() == 1
    assert parallel_state.model_parallel_is_initialized()


def test_initialize_validation(devices):
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(3, 1, devices=devices[:8])
    parallel_state.destroy_model_parallel()


def test_mappings_grads(tp4_mesh, rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)

    def f(x):
        # copy: identity fwd, psum bwd
        def loss(x):
            y = copy_to_tensor_model_parallel_region(x)
            rank = jax.lax.axis_index("tp").astype(jnp.float32)
            return jnp.sum(y) * (rank + 1.0)

        g = jax.grad(loss)(x)
        return g

    g = _smap(f, tp4_mesh, (P(),), P(None))(x)
    # psum of per-rank grads: sum(rank+1 for rank in 0..3) = 10
    np.testing.assert_allclose(np.asarray(g), 10.0, rtol=1e-6)


def test_gather_scatter_roundtrip(tp4_mesh, rng):
    full = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)

    def f(x_shard):
        gathered = gather_from_tensor_model_parallel_region(x_shard)
        return gathered

    out = _smap(f, tp4_mesh, (P(None, "tp"),), P(None, None))(full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full), rtol=1e-6)


def test_sequence_parallel_roundtrip(tp4_mesh, rng):
    full = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)

    def f(x):
        # x arrives replicated; scatter along seq, gather back
        mine = scatter_to_sequence_parallel_region(x)
        back = gather_from_sequence_parallel_region(mine, None, True)
        return back

    out = _smap(f, tp4_mesh, (P(),), P(None))(full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full), rtol=1e-6)


def test_reduce_scatter_sums(tp4_mesh):
    x = jnp.ones((8, 2), jnp.float32)

    def f(x):
        return reduce_scatter_to_sequence_parallel_region(x)

    out = _smap(f, tp4_mesh, (P(),), P("tp"))(x)
    # each rank contributes ones; reduce-scatter over 4 ranks → 4s
    np.testing.assert_allclose(np.asarray(out), 4.0)


def test_column_parallel_linear_parity(tp4_mesh, rng):
    x = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
    col = ColumnParallelLinear(16, 32, gather_output=True)

    def run(x):
        params = col.init(jax.random.PRNGKey(7), x)
        y = col.apply(params, x)
        kfull = jax.lax.all_gather(params["params"]["kernel"], "tp",
                                   axis=1, tiled=True)
        bfull = jax.lax.all_gather(params["params"]["bias"], "tp",
                                   axis=0, tiled=True)
        return y, kfull, bfull

    y, kfull, bfull = _smap(run, tp4_mesh, (P(),), (P(None), P(None), P(None)))(x)
    ref = np.asarray(x) @ np.asarray(kfull) + np.asarray(bfull)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # grad-of-shard_map compile (~2.5 s); forward
# parity + the sp pair below keep the column path live in tier-1
def test_column_parallel_grads_match_dense(tp4_mesh, rng):
    """End-to-end grad parity: column(gather) vs dense reference."""
    x = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((6, 32)), jnp.float32)
    col = ColumnParallelLinear(16, 32, gather_output=True)

    def run(x, t):
        params = col.init(jax.random.PRNGKey(3), x)

        def loss(p, x):
            y = col.apply(p, x)
            return jnp.mean((y - t) ** 2)

        g = jax.grad(loss)(params, x)
        kfull = jax.lax.all_gather(params["params"]["kernel"], "tp", axis=1, tiled=True)
        gk_full = jax.lax.all_gather(g["params"]["kernel"], "tp", axis=1, tiled=True)
        gx = jax.grad(lambda x: loss(params, x))(x)
        return kfull, gk_full, gx

    kfull, gk, gx = _smap(run, tp4_mesh, (P(), P()),
                          (P(None), P(None), P(None)))(x, t)

    def dense_loss(k, x):
        return jnp.mean((jnp.dot(x, k, precision="highest") - t) ** 2)

    gk_ref = jax.grad(dense_loss)(kfull, x)
    gx_ref = jax.grad(dense_loss, argnums=1)(kfull, x)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref), rtol=1e-4, atol=1e-6)


def test_row_parallel_linear_parity(tp4_mesh, rng):
    x = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
    row = RowParallelLinear(16, 8, input_is_parallel=False)

    def run(x):
        params = row.init(jax.random.PRNGKey(11), x)
        y = row.apply(params, x)
        kfull = jax.lax.all_gather(params["params"]["kernel"], "tp",
                                   axis=0, tiled=True)
        return y, kfull, params["params"]["bias"]

    y, kfull, bias = _smap(run, tp4_mesh, (P(),), (P(None), P(None), P(None)))(x)
    ref = np.asarray(x) @ np.asarray(kfull) + np.asarray(bias)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_column_row_pair_sequence_parallel(tp4_mesh, rng):
    """col(SP, no-gather) → row(SP) pipeline reproduces the dense MLP— the
    core Megatron SP data path (layers.py:311-412)."""
    s, b, h, ffn = 8, 2, 16, 32
    x = jnp.asarray(rng.standard_normal((s, b, h)), jnp.float32)
    col = ColumnParallelLinear(h, ffn, gather_output=False,
                              sequence_parallel_enabled=True)
    row = RowParallelLinear(ffn, h, input_is_parallel=True,
                           sequence_parallel_enabled=True)

    def run(x):  # x arrives sharded [s/tp, b, h]
        pc = col.init(jax.random.PRNGKey(5), x)
        mid = col.apply(pc, x)
        pr = row.init(jax.random.PRNGKey(6), mid)
        out = row.apply(pr, mid)
        kc = jax.lax.all_gather(pc["params"]["kernel"], "tp", axis=1, tiled=True)
        bc = jax.lax.all_gather(pc["params"]["bias"], "tp", axis=0, tiled=True)
        kr = jax.lax.all_gather(pr["params"]["kernel"], "tp", axis=0, tiled=True)
        br = pr["params"]["bias"]
        return out, kc, bc, kr, br

    out, kc, bc, kr, br = _smap(
        run, tp4_mesh, (P("tp"),),
        (P("tp"), P(None), P(None), P(None), P(None)))(x)
    assert out.shape == x.shape
    hid = np.asarray(x) @ np.asarray(kc) + np.asarray(bc)
    ref = hid @ np.asarray(kr) + np.asarray(br)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding(tp4_mesh, rng):
    vocab, dim = 32, 8
    ids = jnp.asarray(rng.integers(0, vocab, (3, 5)), jnp.int32)
    emb = VocabParallelEmbedding(vocab, dim)

    def run(ids):
        params = emb.init(jax.random.PRNGKey(2), ids)
        y = emb.apply(params, ids)
        wfull = jax.lax.all_gather(params["params"]["embedding"], "tp",
                                   axis=0, tiled=True)
        return y, wfull

    y, wfull = _smap(run, tp4_mesh, (P(),), (P(None), P(None)))(ids)
    ref = np.asarray(wfull)[np.asarray(ids)]
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy(tp4_mesh, rng, smoothing):
    b, s, vocab = 2, 6, 32
    logits = jnp.asarray(rng.standard_normal((b, s, vocab)), jnp.float32)
    target = jnp.asarray(rng.integers(0, vocab, (b, s)), jnp.int32)

    def run(logits, target):
        return vocab_parallel_cross_entropy(logits, target, smoothing)

    loss = _smap(run, tp4_mesh, (P(None, None, "tp"), P()), P(None))(logits, target)

    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
    if smoothing > 0:
        sm = smoothing * vocab / (vocab - 1)
        ref = (1 - sm) * nll - sm * jnp.mean(logp, axis=-1)
    else:
        ref = nll
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_vocab_parallel_cross_entropy_grad(tp4_mesh, rng):
    b, vocab = 4, 32
    logits = jnp.asarray(rng.standard_normal((b, vocab)), jnp.float32)
    target = jnp.asarray(rng.integers(0, vocab, (b,)), jnp.int32)

    def run(logits, target):
        def loss(lg):
            return jnp.mean(vocab_parallel_cross_entropy(lg, target))

        g_shard = jax.grad(loss)(logits)
        return jax.lax.all_gather(g_shard, "tp", axis=1, tiled=True)

    g = _smap(run, tp4_mesh, (P(None, "tp"), P()), P(None))(logits, target)
    ref = jax.grad(
        lambda lg: jnp.mean(-jnp.take_along_axis(
            jax.nn.log_softmax(lg), target[:, None], axis=1)))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref), rtol=1e-4, atol=1e-6)


def test_rng_tracker():
    from apex_tpu.transformer.tensor_parallel import (
        get_rng_state_tracker,
        model_parallel_seed,
    )

    model_parallel_seed(1234)
    tracker = get_rng_state_tracker()
    with tracker.fork() as k1:
        pass
    with tracker.fork() as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    # snapshot/restore reproduces the stream
    state = tracker.get_states()
    with tracker.fork() as k3:
        pass
    tracker.set_states(state)
    with tracker.fork() as k3b:
        pass
    assert np.array_equal(np.asarray(k3), np.asarray(k3b))


def test_microbatch_calculators():
    from apex_tpu.transformer.microbatches import build_num_microbatches_calculator

    c = build_num_microbatches_calculator(0, None, 64, 4, 2)
    assert c.get() == 8
    r = build_num_microbatches_calculator(0, [16, 16, 96], 64, 4, 2)
    assert r.get() == 2  # start 16 / (4*2)
    r.update(96, True)
    assert r.get_current_global_batch_size() == 64
    with pytest.raises(ValueError):
        build_num_microbatches_calculator(0, None, 30, 4, 2)


def test_batch_samplers():
    from apex_tpu.transformer._data import (
        MegatronPretrainingRandomSampler,
        MegatronPretrainingSampler,
    )

    s = MegatronPretrainingSampler(total_samples=32, consumed_samples=0,
                                   micro_batch_size=2, data_parallel_rank=1,
                                   data_parallel_size=2)
    batches = list(s)
    assert all(len(b) == 2 for b in batches)
    assert batches[0] == [2, 3]  # rank 1's slice of the first global batch

    r = MegatronPretrainingRandomSampler(
        total_samples=32, consumed_samples=0, micro_batch_size=2,
        data_parallel_rank=0, data_parallel_size=2)
    rb = list(r)
    assert all(len(b) == 2 for b in rb)
    flat = [i for b in rb for i in b]
    assert len(set(flat)) == len(flat)  # no duplicates within epoch
