"""Packed (multi-tensor) optimizer paths vs the per-leaf fused optimizers.

VERDICT r1 weak #8: the packed path must cover LAMB/NovoGrad/Adagrad, not
just Adam/SGD, and prove parity with the per-leaf updates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.packed_update import (
    packed_adagrad_update,
    packed_novograd_update,
    segment_ids_for_spec,
)
from apex_tpu.optimizers import FusedAdagrad, FusedLAMB, FusedNovoGrad
from apex_tpu.utils.packing import make_packed_spec, pack_pytree


def make_params(rng):
    # mixed shapes/sizes: embeddings, matmul weights, biases, norm scales
    return {
        "embed": jnp.asarray(rng.standard_normal((40, 16)), jnp.float32),
        "w1": jnp.asarray(rng.standard_normal((16, 32)), jnp.float32),
        "b1": jnp.asarray(rng.standard_normal((32,)), jnp.float32),
        "scale": jnp.asarray(rng.standard_normal((16,)), jnp.float32),
    }


def make_grads(rng, params):
    return jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32) * 0.1,
        params)


def assert_trees_close(a, b, **kw):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


@pytest.mark.parametrize("wd,adam_w", [(0.01, True), (0.01, False),
                                       (0.0, True)])
def test_packed_lamb_matches_per_leaf(wd, adam_w):
    rng = np.random.default_rng(0)
    params = make_params(rng)
    grads = make_grads(rng, params)

    ref_opt = FusedLAMB(lr=1e-2, weight_decay=wd, adam_w_mode=adam_w)
    pk_opt = FusedLAMB(lr=1e-2, weight_decay=wd, adam_w_mode=adam_w,
                       packed=True)
    ref_p, ref_s = params, ref_opt.init(params)
    pk_p, pk_s = params, pk_opt.init(params)
    for _ in range(3):
        ref_p, ref_s = ref_opt.step(grads, ref_p, ref_s)
        pk_p, pk_s = pk_opt.step(grads, pk_p, pk_s)
    assert_trees_close(pk_p, ref_p, rtol=1e-5, atol=1e-6)
    # the packed state really is flat
    assert pk_s[0].exp_avg.ndim == 1


def test_packed_lamb_found_inf_and_jit():
    rng = np.random.default_rng(1)
    params = make_params(rng)
    grads = make_grads(rng, params)
    opt = FusedLAMB(lr=1e-2, packed=True)
    state = opt.init(params)

    @jax.jit
    def step(g, p, s, inf):
        return opt.step(g, p, s, found_inf=inf)

    new_p, _ = step(grads, params, state, jnp.bool_(True))
    assert_trees_close(new_p, params, rtol=0, atol=0)  # skipped update
    new_p, _ = step(grads, params, state, jnp.bool_(False))
    assert any(not np.allclose(a, b) for a, b in
               zip(jax.tree.leaves(new_p), jax.tree.leaves(params)))


@pytest.mark.parametrize("cls_name", ["FusedAdagrad", "FusedNovoGrad"])
def test_packed_flag_on_optimizer_classes(cls_name):
    """FusedAdagrad/FusedNovoGrad(packed=True) match their per-leaf step."""
    import apex_tpu.optimizers as opts

    cls = getattr(opts, cls_name)
    rng = np.random.default_rng(10)
    params = make_params(rng)
    grads = make_grads(rng, params)
    ref = cls(lr=1e-2, weight_decay=0.01)
    pk = cls(lr=1e-2, weight_decay=0.01, packed=True)
    ref_p, ref_s = params, ref.init(params)
    pk_p, pk_s = params, pk.init(params)
    for _ in range(3):
        ref_p, ref_s = ref.step(grads, ref_p, ref_s)
        pk_p, pk_s = pk.step(grads, pk_p, pk_s)
    assert_trees_close(pk_p, ref_p, rtol=1e-5, atol=1e-6)
    inner = pk_s[0]  # AdagradState.sum_sq / NovoGradState.exp_avg
    flat_field = inner.sum_sq if cls_name == "FusedAdagrad" else inner.exp_avg
    assert flat_field.ndim == 1  # state lives flat


def test_packed_novograd_matches_per_leaf():
    rng = np.random.default_rng(2)
    params = make_params(rng)
    grads = make_grads(rng, params)
    opt = FusedNovoGrad(lr=1e-2, weight_decay=0.01)
    spec = make_packed_spec(params)
    seg_ids = segment_ids_for_spec(spec)

    ref_p, ref_s = params, opt.init(params)
    flat_p = pack_pytree(params).flat
    flat_m = jnp.zeros_like(flat_p)
    seg_v = jnp.zeros((spec.num_leaves + 1,), jnp.float32)
    for step_i in range(1, 4):
        ref_p, ref_s = opt.step(grads, ref_p, ref_s)
        from apex_tpu.optimizers._common import bias_corrections

        bc1, bc2 = bias_corrections(jnp.int32(step_i), 0.95, 0.98)
        flat_g = pack_pytree(grads, dtype=jnp.float32).flat
        flat_p, flat_m, seg_v = packed_novograd_update(
            flat_g, flat_p, flat_m, seg_v, seg_ids,
            num_leaves=spec.num_leaves, lr=1e-2, beta1=0.95, beta2=0.98,
            beta3=1.0, eps=1e-8, weight_decay=0.01,  # grad_averaging=False
            bias_correction1=bc1, bias_correction2=bc2,
            is_first_step=jnp.bool_(step_i == 1), reg_inside_moment=False)
    from apex_tpu.utils.packing import unpack_pytree

    assert_trees_close(unpack_pytree(flat_p, spec), ref_p,
                       rtol=1e-5, atol=1e-6)
    # per-tensor second moment: one scalar per leaf
    assert seg_v.shape == (spec.num_leaves + 1,)


def test_packed_adagrad_matches_per_leaf():
    rng = np.random.default_rng(3)
    params = make_params(rng)
    grads = make_grads(rng, params)
    opt = FusedAdagrad(lr=1e-2, weight_decay=0.01)
    spec = make_packed_spec(params)

    ref_p, ref_s = params, opt.init(params)
    flat_p = pack_pytree(params).flat
    flat_h = jnp.zeros_like(flat_p)
    for _ in range(3):
        ref_p, ref_s = opt.step(grads, ref_p, ref_s)
        flat_g = pack_pytree(grads, dtype=jnp.float32).flat
        flat_p, flat_h = packed_adagrad_update(
            flat_g, flat_p, flat_h, lr=1e-2, eps=1e-10, weight_decay=0.01)
    from apex_tpu.utils.packing import unpack_pytree

    assert_trees_close(unpack_pytree(flat_p, spec), ref_p,
                       rtol=1e-5, atol=1e-6)
