"""ViT model family: torch-oracle parity + tp sharding smoke.

Logits of :class:`apex_tpu.models.ViTForImageClassification` must match
``transformers.ViTForImageClassification`` (torch CPU) with identical
weights — patch-conv-to-dense weight transpose, [CLS]/position handling,
exact-gelu MLP, and pre-LN blocks all have to line up.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import ViTConfig, ViTForImageClassification

CFG = ViTConfig(image_size=32, patch_size=8, hidden_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                intermediate_size=152, num_labels=10)


def _hf_model(cfg, seed=0):
    torch = pytest.importorskip("torch")
    from transformers import ViTConfig as HFConfig
    from transformers import ViTForImageClassification as HFModel

    torch.manual_seed(seed)
    hf_cfg = HFConfig(
        image_size=cfg.image_size, patch_size=cfg.patch_size,
        num_channels=cfg.num_channels, hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        intermediate_size=cfg.intermediate_size,
        layer_norm_eps=cfg.layer_norm_eps, num_labels=cfg.num_labels,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    return HFModel(hf_cfg).eval()


def _port_weights(hf, cfg):
    sd = {k: np.asarray(v.detach().numpy()) for k, v in hf.state_dict().items()}
    p = cfg.patch_size

    def lin(name):
        return {"kernel": jnp.asarray(sd[name + ".weight"].T),
                "bias": jnp.asarray(sd[name + ".bias"])}

    # conv [hid, C, ph, pw] -> dense [(ph, pw, C) -> hid]
    conv = sd["vit.embeddings.patch_embeddings.projection.weight"]
    patch_kernel = conv.transpose(2, 3, 1, 0).reshape(
        p * p * cfg.num_channels, cfg.hidden_size)

    params = {
        "patch_kernel": jnp.asarray(patch_kernel),
        "patch_bias": jnp.asarray(
            sd["vit.embeddings.patch_embeddings.projection.bias"]),
        "cls_token": jnp.asarray(sd["vit.embeddings.cls_token"]),
        "position_embeddings": jnp.asarray(
            sd["vit.embeddings.position_embeddings"]),
        "layernorm": {"scale": jnp.asarray(sd["vit.layernorm.weight"]),
                      "bias": jnp.asarray(sd["vit.layernorm.bias"])},
        "classifier_kernel": jnp.asarray(sd["classifier.weight"].T),
        "classifier_bias": jnp.asarray(sd["classifier.bias"]),
    }
    for i in range(cfg.num_hidden_layers):
        pre = f"vit.encoder.layer.{i}."
        params[f"layer_{i}"] = {
            "layernorm_before": {
                "scale": jnp.asarray(sd[pre + "layernorm_before.weight"]),
                "bias": jnp.asarray(sd[pre + "layernorm_before.bias"])},
            "layernorm_after": {
                "scale": jnp.asarray(sd[pre + "layernorm_after.weight"]),
                "bias": jnp.asarray(sd[pre + "layernorm_after.bias"])},
            "attention": {
                "query": lin(pre + "attention.attention.query"),
                "key": lin(pre + "attention.attention.key"),
                "value": lin(pre + "attention.attention.value"),
                "output": lin(pre + "attention.output.dense"),
            },
            "intermediate": lin(pre + "intermediate.dense"),
            "output": lin(pre + "output.dense"),
        }
    return {"params": params}


def test_logits_match_torch_oracle(rng):
    torch = pytest.importorskip("torch")
    hf = _hf_model(CFG)
    params = _port_weights(hf, CFG)

    pixels = rng.standard_normal(
        (2, CFG.image_size, CFG.image_size, 3)).astype(np.float32)
    with torch.no_grad():
        # HF takes NCHW
        ref = hf(torch.tensor(pixels.transpose(0, 3, 1, 2))).logits.numpy()

    model = ViTForImageClassification(CFG)
    got = np.asarray(model.apply(params, jnp.asarray(pixels)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_train_step_runs(rng):
    model = ViTForImageClassification(CFG)
    pixels = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray([1, 3])
    params = model.init(jax.random.PRNGKey(0), pixels)

    def loss_fn(p):
        logits = model.apply(p, pixels)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
        return jnp.mean(lse - tgt)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree.leaves(grads))


def test_tensor_parallel_matches_single(devices, rng):
    """tp=2 sharded logits == unsharded logits."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.utils.compat import NO_REP_CHECK, shard_map

    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(2, 1,
                                                    devices=devices[:2])
    try:
        model = ViTForImageClassification(CFG)
        pixels = jnp.asarray(rng.standard_normal((2, 32, 32, 3)),
                             jnp.float32)
        params = model.init(jax.random.PRNGKey(0), pixels)
        ref = model.apply(params, pixels)

        def shard(path, leaf):
            name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
            if any(k in name for k in ("query/", "key/", "value/",
                                       "intermediate/")):
                # column-parallel: kernel [in, out/tp], bias [out/tp]
                return P(None, "tp") if leaf.ndim == 2 else P("tp")
            if name.endswith("output/kernel"):
                return P("tp", None)  # row-parallel input shard
            return P()  # row-parallel biases, norms, embeds: replicated

        specs = jax.tree_util.tree_map_with_path(shard, params)
        with mesh:
            out = jax.jit(shard_map(
                lambda p, x: model.apply(p, x), mesh=mesh,
                in_specs=(specs, P()), out_specs=P(),
                **NO_REP_CHECK))(params, pixels)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        parallel_state.destroy_model_parallel()
