"""Rolling fleet upgrades: health-gated rolling reload, canary
replica, automatic fleet rollback (ISSUE 18).

THE acceptance run: a 3-replica fleet under a ~2x open-loop overload
rolls every replica to a newer committed checkpoint — canary first
with traffic pinned and a gate verdict, then the remaining waves —
with **zero dropped streams**, every served stream token-identical to
its unperturbed single-version reference, all replicas converged on
the new ``weights_step``, and exactly one decode compile per engine.

The chaos variants: a candidate that validates clean but serves
measurably worse fails the canary gate → automatic halt + fleet
rollback leaves every replica **bit-exact** on the old weights, and
the gated rollout's goodput strictly beats the same rollout with the
gate disabled; a candidate corrupted mid-rollout is refused
first-class and rolled back; a canary killed mid-verdict-window
aborts the rollout and its streams replay losslessly on the
old-version survivors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging, obs
from apex_tpu import resilience as rz
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs.slo import SLOReport
from apex_tpu.resilience.fault_injection import (
    CorruptCandidateMidRollout,
    KillCanary,
    RegressingWeights,
)

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96
STEP_S = 0.25
BOOT_STEP = 100
TARGET = 200


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


@pytest.fixture(scope="module")
def _fleet_mod(model, params):
    """Three independent 2-slot dense engines — the fleet.  Module
    -scoped (each jit family compiles once per engine)."""
    return tuple(sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                                 prefill_len=32) for _ in range(3))


@pytest.fixture
def fleet_engines(_fleet_mod, params):
    """Reset before each test AND restore the boot weights after — a
    rollout test leaves candidate params swapped in."""
    for e in _fleet_mod:
        e.swap_params(params)
        e.reset()
    yield _fleet_mod
    for e in _fleet_mod:
        e.swap_params(params)
        e.reset()


@pytest.fixture(scope="module")
def _ref_mod(model, params):
    return sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=32)


@pytest.fixture(scope="module")
def isolated_tokens(_ref_mod):
    """``fn(request) -> tokens``: the request's stream run alone on a
    FIFO scheduler — the unperturbed single-version reference."""
    eng = _ref_mod
    memo = {}

    def run(request):
        key = (tuple(request.prompt), request.max_new_tokens,
               request.eos_id, request.temperature, request.top_k,
               request.seed)
        if key not in memo:
            eng.reset()
            sched = sv.ContinuousBatchingScheduler(eng, max_queue=4)
            sched.submit(sv.Request("ref", request.prompt,
                                    max_new_tokens=request.max_new_tokens,
                                    eos_id=request.eos_id,
                                    temperature=request.temperature,
                                    top_k=request.top_k,
                                    seed=request.seed))
            memo[key] = sched.run()["ref"].tokens
        return memo[key]

    return run


def _prompt(seed, n=8):
    return [int(x)
            for x in np.random.default_rng(seed).integers(0, 128, n)]


def _mutated(tree, delta):
    return jax.tree.map(
        lambda l: l + delta if jnp.issubdtype(l.dtype, jnp.floating)
        else l, tree)


def _tree_bytes_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(fa, fb))


def _mk_fleet(engines, clk, *, max_queue=16, config=None):
    scheds = {
        f"r{i}": sv.ContinuousBatchingScheduler(
            e, max_queue=max_queue, log_interval=10 ** 9, clock=clk)
        for i, e in enumerate(engines)}
    return sv.FleetRouter(scheds,
                          config=config if config is not None
                          else sv.FleetConfig())


def _mk_reloaders(router, root, params, *, current_step=BOOT_STEP):
    return {name: sv.HotReloader(router.replica(name), str(root),
                                 like={"params": params},
                                 params_key="params",
                                 current_step=current_step)
            for name in router.replica_names}


def _workload(n=16, *, max_new=5, deadline_s=60.0, seed_base=300,
              rate=8.0):
    """~2x overload: n requests arrive inside n/rate seconds of virtual
    time while the 3x2-slot fleet needs several times that."""
    prompts = [_prompt(seed_base + i) for i in range(n)]
    return sv.make_workload(prompts, sv.uniform_arrivals(n, rate),
                            max_new_tokens=max_new,
                            deadline_s=deadline_s, rid_prefix="ro")


def _chain(*hooks):
    def hook(step, router):
        for h in hooks:
            h(step, router)
    return hook


def _drive_to_terminal(router, clk, ctl, *extra_hooks, limit=300):
    """The workload can drain before the rollout's last wave — keep
    stepping the idle fleet until the controller lands terminal."""
    steps = 0
    while not ctl.done and steps < limit:
        router.step()
        clk.advance(STEP_S)
        ctl.advance()
        for h in extra_hooks:
            h(10_000 + steps, router)
        steps += 1
    assert ctl.done, f"rollout never terminal: {ctl.status}"


def _assert_zero_dropped(out, wl):
    """Zero admitted streams dropped: everything offered either shed
    at submit (counted) or finished with full service."""
    admitted = [r for r in wl.requests if r.rid not in set(out.rejected)]
    for req in admitted:
        res = out.results.get(req.rid)
        assert res is not None and res.finish_reason \
            in sv.SERVED_REASONS, \
            f"{req.rid} dropped: {res and res.finish_reason}"


class _EventTap:
    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


# ---------------------------------------------------------------------------
# gate units
# ---------------------------------------------------------------------------


def _slo(completed, offered, *, tpot_p95=0.25, ttft_p95=0.5,
         goodput=None):
    return SLOReport(offered=offered, completed=completed,
                     incomplete=offered - completed, duration_s=1.0,
                     throughput_rps=None,
                     output_tokens=completed * 5, tokens_per_s=None,
                     ttft={"p95": ttft_p95}, tpot={"p95": tpot_p95},
                     queue_wait={}, total={}, goodput=goodput,
                     deadline_misses=0)


class TestCanaryGate:
    def test_validation(self):
        with pytest.raises(ValueError, match="ratios"):
            sv.CanaryGate(tpot_ratio=0.0)
        with pytest.raises(ValueError, match="min_samples"):
            sv.CanaryGate(min_samples=0)

    def test_identical_arms_pass(self):
        ok, reasons = sv.CanaryGate().verdict(_slo(5, 6), _slo(5, 6))
        assert ok and reasons == []

    def test_fails_closed_on_empty_canary(self):
        """A canary that served nothing in the window FAILS — silence
        is itself a regression signal."""
        ok, reasons = sv.CanaryGate().verdict(_slo(0, 4), _slo(6, 6))
        assert not ok
        assert any("fail-closed" in r for r in reasons)

    def test_tpot_regression_fails(self):
        ok, reasons = sv.CanaryGate(tpot_ratio=1.5).verdict(
            _slo(5, 6, tpot_p95=0.50), _slo(5, 6, tpot_p95=0.25))
        assert not ok
        assert any("tpot" in r for r in reasons)

    def test_ttft_regression_fails(self):
        ok, reasons = sv.CanaryGate(ttft_ratio=1.5).verdict(
            _slo(5, 6, ttft_p95=2.0), _slo(5, 6, ttft_p95=0.5))
        assert not ok
        assert any("ttft" in r for r in reasons)

    def test_completion_rate_regression_fails(self):
        ok, reasons = sv.CanaryGate(completion_margin=0.1).verdict(
            _slo(5, 10), _slo(10, 10))
        assert not ok
        assert any("completion" in r for r in reasons)

    def test_goodput_regression_fails(self):
        ok, reasons = sv.CanaryGate(goodput_margin=0.05).verdict(
            _slo(5, 5, goodput=0.5), _slo(5, 5, goodput=0.9))
        assert not ok
        assert any("goodput" in r for r in reasons)

    def test_thin_baseline_skips_comparisons(self):
        """No baseline samples → only the fail-closed check applies
        (the guard keeps the gate honest on thin windows)."""
        ok, reasons = sv.CanaryGate().verdict(
            _slo(3, 3, tpot_p95=9.9), _slo(0, 0))
        assert ok and reasons == []

    def test_non_finite_series_skipped(self):
        ok, _ = sv.CanaryGate().verdict(
            _slo(5, 6, tpot_p95=float("nan")), _slo(5, 6))
        assert ok

    def test_rollout_config_validation(self):
        with pytest.raises(ValueError, match="batch_size"):
            sv.RolloutConfig(batch_size=0)
        with pytest.raises(ValueError, match="health_window_steps"):
            sv.RolloutConfig(health_window_steps=-1)
        with pytest.raises(ValueError, match="canary_fraction"):
            sv.RolloutConfig(canary_fraction=0.0)
        with pytest.raises(ValueError, match="canary_fraction"):
            sv.RolloutConfig(canary_fraction=1.5)
        with pytest.raises(ValueError, match="canary_window_steps"):
            sv.RolloutConfig(canary_window_steps=0)


# ---------------------------------------------------------------------------
# controller + pin units
# ---------------------------------------------------------------------------


class TestControllerUnits:
    def test_reloaders_must_cover_fleet(self, fleet_engines, params,
                                        tmp_path):
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reloaders = _mk_reloaders(router, tmp_path, params)
        del reloaders["r2"]
        with pytest.raises(ValueError, match="cover the fleet"):
            sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(gate=None))

    def test_reloader_must_wrap_router_scheduler(self, fleet_engines,
                                                 params, tmp_path):
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reloaders = _mk_reloaders(router, tmp_path, params)
        reloaders["r0"] = sv.HotReloader(
            router.replica("r1"), str(tmp_path),
            like={"params": params}, params_key="params",
            current_step=BOOT_STEP)
        with pytest.raises(ValueError, match="different scheduler"):
            sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(gate=None))

    def test_gated_requires_recorder(self, fleet_engines, params,
                                     tmp_path):
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        with pytest.raises(ValueError, match="recorder"):
            sv.RollingReloadController(
                router, _mk_reloaders(router, tmp_path, params))

    def test_start_twice_refused(self, fleet_engines, params, tmp_path):
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        rz.save_checkpoint(str(tmp_path), TARGET, {"params": params})
        ctl = sv.RollingReloadController(
            router, _mk_reloaders(router, tmp_path, params),
            config=sv.RolloutConfig(gate=None))
        assert ctl.start(step=TARGET) == TARGET
        with pytest.raises(RuntimeError, match="one controller"):
            ctl.start(step=TARGET)

    def test_start_without_target_refused(self, fleet_engines, params,
                                          tmp_path):
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        ctl = sv.RollingReloadController(
            router, _mk_reloaders(router, tmp_path, params),
            config=sv.RolloutConfig(gate=None))
        with pytest.raises(ValueError, match="no target step"):
            ctl.start()                 # empty root: nothing committed

    def test_single_replica_fleet_refused(self, fleet_engines, params,
                                          tmp_path):
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines[:1], clk)
        ctl = sv.RollingReloadController(
            router, _mk_reloaders(router, tmp_path, params),
            config=sv.RolloutConfig(gate=None))
        with pytest.raises(ValueError, match="2 replicas"):
            ctl.start(step=TARGET)

    def test_pin_traffic_validation(self, fleet_engines):
        router = _mk_fleet(fleet_engines, sv.VirtualClock())
        with pytest.raises(KeyError):
            router.pin_traffic("nope", fraction=0.5)
        with pytest.raises(ValueError, match="fraction"):
            router.pin_traffic("r0", fraction=0.0)
        with pytest.raises(ValueError, match="fraction"):
            router.pin_traffic("r0", fraction=1.5)

    def test_pin_traffic_exact_seeded_split_and_log(self,
                                                    fleet_engines):
        """The pin is an exact deterministic split (assign_arm rid
        hash), not statistical — and the pinned window's placement log
        comes back from unpin_traffic()."""
        router = _mk_fleet(fleet_engines, sv.VirtualClock())
        router.pin_traffic("r0", fraction=0.5, seed=7)
        rids = [f"p{i}" for i in range(10)]
        for i, rid in enumerate(rids):
            router.submit(sv.Request(rid, _prompt(500 + i),
                                     max_new_tokens=1))
        arms = {rid: sv.assign_arm(rid, fraction=0.5, seed=7)
                for rid in rids}
        assert any(arms.values()) and not all(arms.values())
        for rid in rids:
            placed = router.placement_of(rid)
            assert (placed == "r0") == arms[rid], \
                f"{rid}: arm={arms[rid]} placed={placed}"
        log = router.unpin_traffic()
        assert log == {rid: ("r0" if arms[rid] else
                             router.placement_of(rid))
                       for rid in rids}
        assert router.unpin_traffic() == {}      # log is forgotten
        router.run()

    def test_pin_never_strands_and_skips_unhealthy_canary(
            self, fleet_engines):
        """Losslessness outranks the fraction: a drained canary is
        skipped, the pinned-arm request places on a survivor."""
        router = _mk_fleet(fleet_engines, sv.VirtualClock())
        router.pin_traffic("r0", fraction=1.0, seed=0)
        router.drain("r0")
        router.submit(sv.Request("x", _prompt(510), max_new_tokens=1))
        assert router.placement_of("x") != "r0"
        assert router.unpin_traffic() == {"x": router.placement_of("x")}
        router.rejoin("r0")
        router.run()


# ---------------------------------------------------------------------------
# THE acceptance run: rolling upgrade under overload
# ---------------------------------------------------------------------------


class TestRolloutAcceptance:
    def test_health_gated_rolling_upgrade_zero_drop_token_identical(
            self, fleet_engines, params, tmp_path, isolated_tokens):
        """3 replicas, ~2x open-loop load, gated rolling upgrade to a
        newer committed checkpoint: zero dropped streams, every stream
        token-identical to its unperturbed single-version reference,
        all replicas converge on the new weights_step, one decode
        compile per engine, and the full event ledger in order."""
        obs.metrics.reset()
        # the candidate carries the SAME weights at a newer step:
        # token identity to ONE reference is then exact by
        # construction whichever version served each token
        rz.save_checkpoint(str(tmp_path), TARGET, {"params": params})
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reloaders = _mk_reloaders(router, tmp_path, params)
        wl = _workload()
        with _EventTap() as tap, obs.recording_requests(clock=clk) as rec:
            ctl = sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(
                    step=TARGET, canary_fraction=0.34,
                    canary_window_steps=10, health_window_steps=1,
                    gate=sv.CanaryGate(ttft_ratio=3.0,
                                       completion_margin=0.5)),
                recorder=rec)
            assert ctl.start() == TARGET      # newest committed
            out = sv.LoadGenerator(router, wl, step_time_s=STEP_S,
                                   step_hook=ctl).run()
            _drive_to_terminal(router, clk, ctl)

        assert ctl.state == "promoted", ctl.status
        assert ctl.canary == "r0"
        assert ctl.upgraded == ["r0", "r1", "r2"]
        assert ctl.verdict is not None and ctl.verdict.passed
        assert ctl.verdict.canary["completed"] >= 1

        # zero dropped, every stream bit-identical to its reference
        assert out.rejected == []
        _assert_zero_dropped(out, wl)
        for req in wl.requests:
            assert out.results[req.rid].tokens == isolated_tokens(req), \
                f"{req.rid} diverged across the rolling upgrade"

        # the fleet converged on the candidate; swap pauses recorded
        assert router.weights_steps == {"r0": TARGET, "r1": TARGET,
                                        "r2": TARGET}
        assert set(ctl.swap_pauses) == {"r0", "r1", "r2"}
        assert all(p >= 0.0 for p in ctl.swap_pauses.values())
        for e in fleet_engines:
            assert e.decode_compiles() == 1

        # event ledger: started -> 3 upgrades (canary first, all
        # prefetched) -> pass verdict -> promoted; nothing halted
        assert len(tap.of("serving_rollout_started")) == 1
        ups = tap.of("serving_rollout_replica_upgraded")
        assert [(e["replica"], e["canary"]) for e in ups] \
            == [("r0", True), ("r1", False), ("r2", False)]
        assert all(e["prefetched"] and e["from_step"] == BOOT_STEP
                   and e["step"] == TARGET for e in ups)
        verdicts = tap.of("serving_rollout_canary_verdict")
        assert [e["verdict"] for e in verdicts] == ["pass"]
        assert len(tap.of("serving_rollout_promoted")) == 1
        assert tap.of("serving_rollout_halted") == []
        assert tap.of("serving_rollout_rolled_back") == []

        # the obs bridge surfaced the rollout lifecycle
        snap = obs.snapshot()
        promoted = snap["apex_serving_rollout_promotions_total"]["series"]
        assert promoted and promoted[0]["value"] == 1
        active = snap["apex_serving_rollout_active"]["series"]
        assert active and active[0]["value"] == 0
        upgraded = snap[
            "apex_serving_rollout_replicas_upgraded_total"]["series"]
        assert upgraded and upgraded[0]["value"] == 3

    def test_mixed_version_window_has_no_hybrid_streams(
            self, fleet_engines, params, tmp_path, _ref_mod,
            isolated_tokens):
        """An ungated rolling reload to genuinely different weights:
        mid-rollout the fleet serves two versions, and every finished
        stream matches EITHER the old-version or the new-version
        isolated reference — never a hybrid of the two (cross-version
        captures degrade to a full deterministic replay)."""
        params_v2 = _mutated(params, 0.05)
        rz.save_checkpoint(str(tmp_path), TARGET, {"params": params_v2})
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reloaders = _mk_reloaders(router, tmp_path, params)
        wl = _workload(seed_base=340)
        with _EventTap() as tap:
            ctl = sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(step=TARGET,
                                        health_window_steps=1,
                                        gate=None))
            ctl.start()
            out = sv.LoadGenerator(router, wl, step_time_s=STEP_S,
                                   step_hook=ctl).run()
            _drive_to_terminal(router, clk, ctl)

        assert ctl.state == "promoted", ctl.status
        assert ctl.canary is None               # ungated: no pin phase
        assert tap.of("serving_rollout_canary_verdict") == []
        assert out.rejected == []
        _assert_zero_dropped(out, wl)

        # new-version references, computed on the shared ref engine
        # with the candidate weights swapped in (and restored after)
        _ref_mod.swap_params(params_v2)
        try:
            new_ref = {}
            for req in wl.requests:
                _ref_mod.reset()
                sched = sv.ContinuousBatchingScheduler(_ref_mod,
                                                       max_queue=4)
                sched.submit(sv.Request(
                    "ref", req.prompt,
                    max_new_tokens=req.max_new_tokens,
                    eos_id=req.eos_id, temperature=req.temperature,
                    top_k=req.top_k, seed=req.seed))
                new_ref[req.rid] = sched.run()["ref"].tokens
        finally:
            _ref_mod.swap_params(params)
            _ref_mod.reset()

        n_old = n_new = 0
        for req in wl.requests:
            got = out.results[req.rid].tokens
            old = isolated_tokens(req)
            if got == old:
                n_old += 1
            if got == new_ref[req.rid]:
                n_new += 1
            assert got == old or got == new_ref[req.rid], \
                f"{req.rid} is a hybrid of two weight versions"
        # the mixed window really mixed: both versions finished work,
        # and the two references genuinely disagree somewhere
        assert n_old >= 1 and n_new >= 1
        assert any(isolated_tokens(r) != new_ref[r.rid]
                   for r in wl.requests)

        # mixed-version observability: routed events tagged with the
        # serving step saw both versions during the window
        routed_steps = {e.get("weights_step")
                        for e in tap.of("serving_fleet_routed")}
        assert {BOOT_STEP, TARGET} <= routed_steps
        assert router.weights_steps == {"r0": TARGET, "r1": TARGET,
                                        "r2": TARGET}


# ---------------------------------------------------------------------------
# chaos: the gate earns its keep
# ---------------------------------------------------------------------------


class TestRolloutChaos:
    def _run_regressing(self, engines, params, root, *, gated):
        clk = sv.VirtualClock()
        router = _mk_fleet(engines, clk)
        reloaders = _mk_reloaders(router, root, params)
        wl = _workload(seed_base=360, deadline_s=5.0)
        with _EventTap() as tap, \
                obs.recording_requests(clock=clk) as rec:
            ctl = sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(
                    step=TARGET, canary_fraction=0.5,
                    canary_window_steps=12, health_window_steps=1,
                    gate=(sv.CanaryGate() if gated else None)),
                recorder=(rec if gated else None))
            fault = RegressingWeights(ctl, slow_every=2)
            ctl.start()
            out = sv.LoadGenerator(router, wl, step_time_s=STEP_S,
                                   step_hook=_chain(ctl, fault)).run()
            _drive_to_terminal(router, clk, ctl, fault)
        return router, ctl, fault, out, wl, tap

    def test_regressing_candidate_gate_halts_and_rolls_back_bit_exact(
            self, fleet_engines, params, tmp_path):
        """The headline chaos: a candidate that validates clean but
        serves measurably worse fails the canary gate → automatic halt
        + fleet rollback leaves every replica BIT-EXACT on the old
        weights — and the gated rollout's goodput strictly beats the
        identical rollout with the gate disabled."""
        bad = RegressingWeights.publish(str(tmp_path), params, TARGET)
        router, ctl, fault, out, wl, tap = self._run_regressing(
            fleet_engines, params, tmp_path, gated=True)

        assert ctl.state == "aborted", ctl.status
        assert ctl.abort_reason.startswith("canary_failed")
        assert ctl.verdict is not None and not ctl.verdict.passed
        assert fault.stalls > 0                 # the regression bit
        # halt + rollback: ONE replica (the canary) had upgraded; it
        # rolled back and the whole fleet serves the old bytes again
        rb = tap.of("serving_rollout_rolled_back")
        assert [(e["replicas"], e["names"]) for e in rb] == [(1, "r0")]
        assert len(tap.of("serving_rollout_halted")) == 1
        assert tap.of("serving_rollout_promoted") == []
        assert router.weights_steps == {"r0": BOOT_STEP,
                                        "r1": BOOT_STEP,
                                        "r2": BOOT_STEP}
        for e in fleet_engines:
            assert _tree_bytes_equal(e.params, params), \
                "rollback was not bit-exact"
            assert not _tree_bytes_equal(e.params, bad)
        # the fleet kept serving throughout: zero admitted drops
        _assert_zero_dropped(out, wl)
        g_gated = out.goodput
        assert g_gated is not None

        # the honesty baseline: same candidate, same chaos, gate OFF —
        # the regression promotes fleet-wide and goodput pays for it
        for e in fleet_engines:
            e.swap_params(params)
            e.reset()
        router0, ctl0, fault0, out0, wl0, _ = self._run_regressing(
            fleet_engines, params, tmp_path, gated=False)
        assert ctl0.state == "promoted"         # nothing stopped it
        assert router0.weights_steps == {"r0": TARGET, "r1": TARGET,
                                         "r2": TARGET}
        for e in fleet_engines:
            assert _tree_bytes_equal(e.params, bad)
        assert fault0.stalls > fault.stalls     # whole fleet degraded
        _assert_zero_dropped(out0, wl0)
        g_ungated = out0.goodput
        assert g_ungated is not None
        assert g_gated > g_ungated, \
            f"gated goodput {g_gated} vs ungated {g_ungated}"

    def test_corrupt_candidate_mid_rollout_refused_and_rolled_back(
            self, fleet_engines, params, tmp_path, isolated_tokens):
        """The candidate's bytes rot AFTER the canary upgraded: the
        next wave's reload refuses first-class, the rollout halts, and
        the already-upgraded canary rolls back bit-exact — the fleet
        never serves corrupt weights and never drops a stream."""
        rz.save_checkpoint(str(tmp_path), TARGET, {"params": params})
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reloaders = _mk_reloaders(router, tmp_path, params)
        wl = _workload(seed_base=380)
        fault = CorruptCandidateMidRollout(str(tmp_path), TARGET,
                                           at_step=6)
        with _EventTap() as tap, \
                obs.recording_requests(clock=clk) as rec:
            ctl = sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(
                    step=TARGET, canary_fraction=0.34,
                    canary_window_steps=10, health_window_steps=1,
                    gate=sv.CanaryGate(ttft_ratio=3.0,
                                       completion_margin=0.5)),
                recorder=rec)
            ctl.start()
            out = sv.LoadGenerator(router, wl, step_time_s=STEP_S,
                                   step_hook=_chain(ctl, fault)).run()
            _drive_to_terminal(router, clk, ctl, fault)

        assert fault.corrupted
        assert ctl.state == "aborted", ctl.status
        assert "reload_refused:r1" in ctl.abort_reason
        # the canary passed its gate BEFORE the corruption landed on
        # the next wave — the verdict is not what halted this rollout
        assert ctl.verdict is not None and ctl.verdict.passed
        rb = tap.of("serving_rollout_rolled_back")
        assert [(e["replicas"], e["names"]) for e in rb] == [(1, "r0")]
        assert router.weights_steps == {"r0": BOOT_STEP,
                                        "r1": BOOT_STEP,
                                        "r2": BOOT_STEP}
        for e in fleet_engines:
            assert _tree_bytes_equal(e.params, params)
        _assert_zero_dropped(out, wl)
        for req in wl.requests:
            if req.rid in out.results:
                assert out.results[req.rid].tokens \
                    == isolated_tokens(req)
        for e in fleet_engines:
            assert e.decode_compiles() == 1

    def test_kill_canary_mid_window_aborts_and_replays_losslessly(
            self, fleet_engines, params, tmp_path, isolated_tokens):
        """The canary dies mid-verdict-window: the rollout halts
        (replica death outranks the verdict), there is nothing live to
        roll back, and every canary stream replays losslessly on the
        old-version survivors — zero dropped, token-identical."""
        rz.save_checkpoint(str(tmp_path), TARGET, {"params": params})
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reloaders = _mk_reloaders(router, tmp_path, params)
        wl = _workload(seed_base=400)
        with _EventTap() as tap, \
                obs.recording_requests(clock=clk) as rec:
            ctl = sv.RollingReloadController(
                router, reloaders,
                config=sv.RolloutConfig(
                    step=TARGET, canary_fraction=0.5,
                    canary_window_steps=10, health_window_steps=1,
                    gate=sv.CanaryGate(completion_margin=0.5)),
                recorder=rec)
            fault = KillCanary(ctl, after_window_steps=2)
            ctl.start()
            out = sv.LoadGenerator(router, wl, step_time_s=STEP_S,
                                   step_hook=_chain(ctl, fault)).run()
            _drive_to_terminal(router, clk, ctl, fault)

        assert fault.killed
        assert ctl.state == "aborted", ctl.status
        assert ctl.abort_reason == "replica_died:r0"
        assert router.state_of("r0") is sv.ReplicaState.DEAD
        assert router.replicas_healthy == 2
        # the dead canary cannot roll back (its scheduler is closed);
        # no OTHER replica had upgraded, so the rollback set is empty
        rb = tap.of("serving_rollout_rolled_back")
        assert [e["replicas"] for e in rb] == [0]
        assert len(tap.of("serving_rollout_halted")) == 1
        assert router.weights_steps["r1"] == BOOT_STEP
        assert router.weights_steps["r2"] == BOOT_STEP
        # the pin died with the rollout: the window log was drained
        assert router.unpin_traffic() == {}
        # lossless: every admitted stream — the canary's included —
        # finished with full service, token-identical to its reference
        _assert_zero_dropped(out, wl)
        for req in wl.requests:
            if req.rid in out.results and out.results[req.rid] \
                    .finish_reason in sv.SERVED_REASONS:
                assert out.results[req.rid].tokens \
                    == isolated_tokens(req)
