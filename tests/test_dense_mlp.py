"""Parity tests for fused_dense / MLP (mirrors tests/L0/run_mlp and
apex/contrib/test/fused_dense)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fused_dense import (
    DenseNoBias,
    FusedDense,
    FusedDenseGeluDense,
    linear_bias,
    linear_gelu_linear,
)
from apex_tpu.mlp import MLP, mlp_forward


def test_linear_bias(rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    b = jnp.asarray(rng.standard_normal(16), jnp.float32)
    np.testing.assert_allclose(np.asarray(linear_bias(x, k, b)),
                               np.asarray(x) @ np.asarray(k) + np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_linear_gelu_linear_grad(rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    k1 = jnp.asarray(rng.standard_normal((8, 16)) * 0.1, jnp.float32)
    b1 = jnp.zeros(16)
    k2 = jnp.asarray(rng.standard_normal((16, 8)) * 0.1, jnp.float32)
    b2 = jnp.zeros(8)

    def ref(x, k1, b1, k2, b2):
        import flax.linen as nn
        with jax.default_matmul_precision("highest"):
            h = nn.gelu(x @ k1 + b1, approximate=True)
            return jnp.sum((h @ k2 + b2) ** 2)

    f = lambda *a: jnp.sum(linear_gelu_linear(*a) ** 2)
    gf = jax.grad(f, argnums=tuple(range(5)))(x, k1, b1, k2, b2)
    gr = jax.grad(ref, argnums=tuple(range(5)))(x, k1, b1, k2, b2)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)


def test_modules(rng):
    x = jnp.asarray(rng.standard_normal((2, 8)), jnp.float32)
    for mod in (FusedDense(16), DenseNoBias(16), FusedDenseGeluDense(32, 16)):
        params = mod.init(jax.random.PRNGKey(0), x)
        y = mod.apply(params, x)
        assert y.shape == (2, 16)
        assert np.isfinite(np.asarray(y)).all()


@pytest.mark.parametrize("activation", ["none", "relu", "sigmoid"])
def test_mlp_parity(rng, activation):
    """Fused MLP vs layer-by-layer reference (tests/L0/run_mlp/test_mlp.py style)."""
    sizes = [8, 16, 12, 4]
    x = jnp.asarray(rng.standard_normal((5, 8)), jnp.float32)
    mlp = MLP(sizes, activation=activation)
    params = mlp.init(jax.random.PRNGKey(1), x)

    def ref_apply(params, x):
        p = params["params"]
        h = np.asarray(x)
        for i in range(3):
            h = h @ np.asarray(p[f"kernel_{i}"]) + np.asarray(p[f"bias_{i}"])
            if i != 2:
                if activation == "relu":
                    h = np.maximum(h, 0)
                elif activation == "sigmoid":
                    h = 1 / (1 + np.exp(-h))
        return h

    y = mlp.apply(params, x)
    np.testing.assert_allclose(np.asarray(y), ref_apply(params, x), rtol=1e-4, atol=1e-5)


def test_mlp_errors():
    with pytest.raises(ValueError):
        mlp_forward(jnp.zeros((2, 4)), [jnp.zeros((4, 4))], [None], "tanh")
    mlp = MLP([4, 8])
    with pytest.raises(ValueError):
        mlp.init(jax.random.PRNGKey(0), jnp.zeros((2, 5)))


def test_packed_adam_matches_treewise(rng):
    """ops.packed_update packed Adam == per-leaf fused Adam math."""
    from apex_tpu.ops.packed_update import packed_adam_update
    from apex_tpu.utils.packing import pack_pytree, unpack_pytree

    params = {"w": jnp.asarray(rng.standard_normal((33, 7)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal(11), jnp.float32)}
    grads = jax.tree.map(lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32), params)
    pbuf = pack_pytree(params, dtype=jnp.float32)
    gbuf = pack_pytree(grads, dtype=jnp.float32)
    m = jnp.zeros_like(pbuf.flat)
    v = jnp.zeros_like(pbuf.flat)
    p_new, m_new, v_new = packed_adam_update(
        gbuf.flat, pbuf.flat, m, v, lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8,
        weight_decay=0.01, bias_correction1=0.1, bias_correction2=0.001)
    got = unpack_pytree(p_new, pbuf.spec)

    def ref_leaf(p, g):
        m = 0.1 * g
        vv = 0.001 * g * g
        return p - 1e-2 * ((m / 0.1) / (jnp.sqrt(vv / 0.001) + 1e-8) + 0.01 * p)

    exp = jax.tree.map(ref_leaf, params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(exp[k]),
                                   rtol=1e-5, atol=1e-6)
