"""ASP 2:4 structured sparsity: mask math, reapplication through the
optimizer, recompute/restore, checkpoint round-trip.

Mirrors apex/contrib/test/sparsity/test_permutation_application-style checks
minus the permutation search (inactive on TPU, see asp.py docstring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.sparsity.sparse_masklib import (
    compute_valid_1d_patterns,
    mn_1d_best,
)
from apex_tpu.optimizers import FusedAdam


@pytest.fixture(autouse=True)
def _reset_asp():
    ASP.reset()
    yield
    ASP.reset()


def test_valid_patterns_enumeration():
    pats = compute_valid_1d_patterns(4, 2)
    assert pats.shape == (6, 4)
    assert np.all(pats.sum(1) == 2)
    assert len({tuple(p) for p in pats}) == 6


def test_mn_1d_best_keeps_two_largest_of_four():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 16)).astype(np.float32)
    mask = np.asarray(mn_1d_best(jnp.asarray(w), 4, 2))
    groups = np.abs(w).reshape(-1, 4)
    kept = mask.reshape(-1, 4)
    assert np.all(kept.sum(1) == 2)
    # the kept pair is exactly the top-2 magnitudes of each group
    for g, k in zip(groups, kept):
        top2 = set(np.argsort(g)[-2:])
        assert set(np.nonzero(k)[0]) == top2


def test_create_mask_groups_along_reduction_axis():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)  # [in, out]
    mask = np.asarray(create_mask(w, "m4n2_1d"))
    # 2 of every 4 along axis -2 (the reduction dim)
    assert np.all(mask.reshape(4, 4, 8).sum(1) == 2)
    # conv HWIO: grouped along I
    w4 = jnp.asarray(rng.standard_normal((3, 3, 8, 16)), jnp.float32)
    m4 = np.asarray(create_mask(w4, "m4n2_1d"))
    assert np.all(m4.sum(2) * 2 == m4.shape[2])


def test_asp_end_to_end_mask_persists_through_training():
    rng = np.random.default_rng(2)
    params = {
        "dense": {"kernel": jnp.asarray(rng.standard_normal((32, 16)),
                                        jnp.float32),
                  "bias": jnp.zeros((16,), jnp.float32)},
    }
    opt = FusedAdam(lr=1e-2)
    pruned, sparse_opt = ASP.prune_trained_model(params, opt)

    # bias (1-D) untouched, kernel 50% sparse
    assert np.all(np.asarray(pruned["dense"]["bias"]) == 0)
    kernel = np.asarray(pruned["dense"]["kernel"])
    assert (kernel == 0).mean() == 0.5

    state = sparse_opt.init(pruned)
    p = pruned
    for _ in range(3):
        grads = jax.tree.map(jnp.ones_like, p)
        p, state = sparse_opt.step(grads, p, state)
    kernel = np.asarray(p["dense"]["kernel"])
    mask = np.asarray(ASP.masks()["dense/kernel"])
    # pruned positions stayed exactly zero across optimizer steps
    assert np.all(kernel[~mask] == 0)
    # surviving positions actually trained
    assert np.abs(kernel[mask]).min() >= 0  # finite
    assert not np.allclose(kernel[mask],
                           np.asarray(pruned["dense"]["kernel"])[mask])


def test_asp_recompute_restores_dense_weights():
    rng = np.random.default_rng(3)
    params = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)}
    ASP.init_model_for_pruning(params, allow_recompute_mask=True, verbosity=0)
    pruned, _ = ASP.compute_sparse_masks(params)
    restored = ASP.restore_pruned(pruned)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(params["w"]), rtol=0, atol=0)


def test_asp_checkpoint_roundtrip():
    rng = np.random.default_rng(4)
    params = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)}
    ASP.init_model_for_pruning(params, verbosity=0)
    _, masks = ASP.compute_sparse_masks(params)
    saved = ASP.state_dict()
    ASP.reset()
    ASP.load_state_dict(saved)
    np.testing.assert_array_equal(np.asarray(ASP.masks()["w"]),
                                  np.asarray(masks["w"]))
    # the restored singleton is functional: masks can be recomputed from
    # new weights (resume-then-reprune flow)
    params2 = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)}
    pruned2, _ = ASP.compute_sparse_masks(params2)
    assert (np.asarray(pruned2["w"]) == 0).mean() == 0.5


def test_asp_name_filters():
    params = {"encoder": {"w": jnp.ones((16, 8))},
              "head": {"w": jnp.ones((16, 8))}}
    masks = ASP.init_model_for_pruning(params, verbosity=0,
                                       disallowed_layer_names=["head"])
    assert "encoder/w" in masks and "head/w" not in masks


def test_permutation_search_improves_retained_magnitude():
    from apex_tpu.contrib.sparsity import (
        accelerated_search_for_good_permutation,
        apply_permutation,
        invert_permutation,
        sum_after_2_to_4,
    )

    # adversarial layout: each stripe holds equal-magnitude columns, so 2:4
    # must prune large entries; mixing stripes recovers magnitude
    rng = np.random.default_rng(7)
    big = np.abs(rng.standard_normal((16, 4))) + 10.0
    small = np.abs(rng.standard_normal((16, 4))) * 0.1
    w = np.concatenate([big, small], axis=1)  # stripe0 all-big, stripe1 all-small

    base = sum_after_2_to_4(w)
    perm = accelerated_search_for_good_permutation(w)
    permuted = apply_permutation(w, perm)
    assert sum_after_2_to_4(permuted) > base
    # permutation is a bijection and invertible
    assert sorted(perm) == list(range(8))
    inv = invert_permutation(perm)
    np.testing.assert_array_equal(apply_permutation(permuted, inv), w)


def test_permutation_search_identity_when_nothing_helps():
    from apex_tpu.contrib.sparsity import (
        accelerated_search_for_good_permutation,
    )

    # all-equal magnitudes: no swap can improve retained magnitude
    w = np.ones((8, 8), np.float32)
    perm = accelerated_search_for_good_permutation(w)
    np.testing.assert_array_equal(perm, np.arange(8))


@pytest.mark.slow  # ~200 s of pure-host permutation search (12 instances
# with escape + exhaustive phases) — the quality bar rides the slow tier;
# tier-1 keeps the correctness/bijection/identity witnesses above
def test_permutation_search_beats_plain_greedy():
    """VERDICT r2 item 6 quality bar: the escape + exhaustive phases must
    retain >= the magnitude of plain greedy descent on every instance of a
    fixed random conv-net-shaped suite, and strictly more on at least one
    (i.e. the extra strategies are not dead code)."""
    from apex_tpu.contrib.sparsity import (
        accelerated_search_for_good_permutation,
        apply_permutation,
        sum_after_2_to_4,
    )

    rng = np.random.default_rng(0)
    # conv-net shapes: [out_ch, in_ch] GEMM views of 1x1/3x3 convs
    shapes = [(32, 16), (64, 32), (16, 64), (128, 32)]
    greedy_scores, full_scores = [], []
    for i, (r, c) in enumerate(shapes):
        for trial in range(3):
            # heavy-tailed weights make permutation matter (conv nets have
            # a few dominant channels)
            w = rng.standard_normal((r, c)) * (
                rng.random((1, c)) ** 2 * 3.0 + 0.05)
            greedy = accelerated_search_for_good_permutation(
                w, {"escape_attempts": 0, "exhaustive_window": 0})
            full = accelerated_search_for_good_permutation(w)
            gs = sum_after_2_to_4(apply_permutation(w, greedy))
            fs = sum_after_2_to_4(apply_permutation(w, full))
            assert fs >= gs - 1e-4, (i, trial, gs, fs)
            assert sorted(full) == list(range(c))
            greedy_scores.append(gs)
            full_scores.append(fs)
    assert sum(full_scores) > sum(greedy_scores) + 1e-3, (
        "escape/exhaustive phases never improved on plain greedy")


def test_asp_double_init_raises():
    params = {"w": jnp.ones((16, 8))}
    ASP.init_model_for_pruning(params, verbosity=0)
    with pytest.raises(RuntimeError):
        ASP.init_model_for_pruning(params, verbosity=0)
