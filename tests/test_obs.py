"""Observability subsystem tests (ISSUE 6 tentpole).

Registry correctness (labels, bucket edges, concurrent updates from
threads), Prometheus exposition golden text, JSON export, span
nesting/ordering in exported Chrome trace JSON, the emit_event sink
registry (byte-identical default output), the event → metric bridge —
and THE acceptance runs: a fault-injected supervisor run and a
continuous-batching serving drain, each producing a Prometheus snapshot
whose counters exactly match the injected fault / request counts plus a
loadable Chrome trace, ending with the no-exporter overhead budget.
"""

import json
import logging
import math
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import resilience as rz
from apex_tpu.obs import bridge, metrics, trace
from apex_tpu.obs.metrics import (
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    REGISTRY,
)
from apex_tpu.transformer.pipeline_parallel._timers import Timers


@pytest.fixture
def reg():
    """A private registry — unit tests never touch the process default."""
    return MetricsRegistry()


def _reject_constant(name):
    raise AssertionError(f"non-strict JSON constant {name!r} in export")


@pytest.fixture
def events():
    """Capture structured events BOTH ways the new fan-out offers: the
    parsed log lines (proving the default sink) and a direct sink."""
    sunk = []
    _logging.add_event_sink(sunk.append)
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    logger = logging.getLogger("apex_tpu.events")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)

    def get(kind=None):
        parsed = [json.loads(r) for r in records]
        return parsed if kind is None else [e for e in parsed
                                            if e["event"] == kind]

    get.sunk = sunk
    yield get
    logger.removeHandler(handler)
    _logging.remove_event_sink(sunk.append)


# --------------------------------------------------------------------------
# registry correctness
# --------------------------------------------------------------------------

class TestCounter:
    def test_inc_and_value(self, reg):
        c = reg.counter("apex_t_total", "h")
        assert c.value() == 0.0
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5

    def test_negative_increment_rejected(self, reg):
        c = reg.counter("apex_t_total")
        with pytest.raises(ValueError, match=">= 0"):
            c.inc(-1)
        # NaN slips past a naive `< 0` check, and +Inf past a naive
        # `>= 0` one — either would poison the running total for the
        # life of the process
        with pytest.raises(ValueError, match="finite"):
            c.inc(float("nan"))
        with pytest.raises(ValueError, match="finite"):
            c.inc(float("inf"))
        assert c.value() == 0.0

    def test_labeled_series_are_independent(self, reg):
        c = reg.counter("apex_t_total", "h", ("kind", "site"))
        c.inc(kind="a", site="x")
        c.inc(3, kind="b", site="x")
        assert c.value(kind="a", site="x") == 1.0
        assert c.value(kind="b", site="x") == 3.0
        assert c.value(kind="a", site="y") == 0.0
        assert c.series_count() == 2

    def test_wrong_labels_rejected(self, reg):
        c = reg.counter("apex_t_total", "h", ("kind",))
        with pytest.raises(ValueError, match="labelnames"):
            c.inc()  # missing label
        with pytest.raises(ValueError, match="labelnames"):
            c.inc(kind="a", extra="b")

    def test_name_conventions_enforced_at_registration(self, reg):
        for bad in ("step_total", "apex_BadCase", "apex-dash", "apex_"):
            # "apex_" alone fails [a-z0-9_]+ needing >= 1 char after apex_
            if bad == "apex_":
                continue
            with pytest.raises(ValueError, match="must match"):
                reg.counter(bad)
        with pytest.raises(ValueError, match="invalid label name"):
            reg.counter("apex_ok_total", "h", ("BadLabel",))

    def test_reregistration_same_signature_returns_same_object(self, reg):
        a = reg.counter("apex_t_total", "h", ("k",))
        b = reg.counter("apex_t_total", "other help", ("k",))
        assert a is b

    def test_conflicting_reregistration_raises(self, reg):
        reg.counter("apex_t_total", "h", ("k",))
        with pytest.raises(ValueError, match="conflicting"):
            reg.counter("apex_t_total", "h", ("other",))
        with pytest.raises(ValueError, match="conflicting"):
            reg.gauge("apex_t_total")


class TestGauge:
    def test_set_inc_dec(self, reg):
        g = reg.gauge("apex_t_depth")
        g.set(5)
        g.inc()
        g.dec(2.5)
        assert g.value() == 3.5

    def test_set_function_evaluates_at_read_time(self, reg):
        g = reg.gauge("apex_t_age")
        box = {"v": 1.0}
        g.set_function(lambda: box["v"])
        assert g.value() == 1.0
        box["v"] = 42.0
        assert g.value() == 42.0
        snap = reg.snapshot()["apex_t_age"]["series"]
        assert snap == [{"labels": {}, "value": 42.0}]
        g.set_function(None)
        assert g.value() == 0.0  # unbound: back to pushed value

    def test_function_failure_exports_nan_not_crash(self, reg, tmp_path):
        g = reg.gauge("apex_t_age")
        g.set_function(lambda: 1 / 0)
        [serie] = reg.snapshot()["apex_t_age"]["series"]
        assert serie["value"] != serie["value"]  # NaN
        assert "NaN" in reg.prometheus_text()
        # the JSON export must stay STRICT-parser valid: NaN -> null
        path = str(tmp_path / "m.json")
        reg.write_json(path)
        with open(path) as f:
            loaded = json.load(f, parse_constant=_reject_constant)
        [serie] = loaded["metrics"]["apex_t_age"]["series"]
        assert serie["value"] is None


class TestHistogram:
    def test_default_buckets_are_fixed_and_log_spaced(self):
        assert len(LATENCY_BUCKETS_S) == 25
        assert LATENCY_BUCKETS_S[0] == pytest.approx(1e-4)
        assert LATENCY_BUCKETS_S[-1] == pytest.approx(1e2)
        ratios = [b / a for a, b in zip(LATENCY_BUCKETS_S,
                                        LATENCY_BUCKETS_S[1:])]
        for r in ratios:  # 4 per decade
            assert r == pytest.approx(10 ** 0.25, rel=1e-6)

    def test_bucket_edges_are_upper_inclusive(self, reg):
        h = reg.histogram("apex_t_lat_seconds", "h", buckets=(1.0, 10.0))
        h.observe(1.0)    # exactly on an edge -> that bucket (le)
        h.observe(0.5)
        h.observe(10.0)
        h.observe(11.0)   # past the last edge -> +Inf
        assert h.cumulative_counts() == (2, 3, 4)
        assert h.count() == 4
        assert h.sum() == pytest.approx(22.5)

    def test_non_finite_observations_rejected(self, reg):
        h = reg.histogram("apex_t_lat_seconds", buckets=(1.0,))
        for bad in (float("nan"), float("inf"), -float("inf")):
            with pytest.raises(ValueError, match="non-finite"):
                h.observe(bad)
        assert h.count() == 0

    def test_le_label_is_reserved_for_histograms(self, reg):
        with pytest.raises(ValueError, match="reserved"):
            reg.histogram("apex_t_lat_seconds", labelnames=("le",))
        reg.counter("apex_t_total", "le is fine elsewhere", ("le",))

    def test_degenerate_buckets_rejected(self, reg):
        with pytest.raises(ValueError, match="at least one"):
            reg.histogram("apex_t_lat_seconds", buckets=())
        with pytest.raises(ValueError, match="strictly"):
            reg.histogram("apex_t_lat_seconds", buckets=(1.0, 1.0))

    def test_conflicting_buckets_on_reregistration(self, reg):
        reg.histogram("apex_t_lat_seconds", buckets=(1.0,))
        with pytest.raises(ValueError, match="conflicting"):
            reg.histogram("apex_t_lat_seconds", buckets=(2.0,))

    def test_labeled_histogram_series(self, reg):
        h = reg.histogram("apex_t_lat_seconds", "h", ("op",),
                          buckets=(1.0,))
        h.observe(0.5, op="save")
        h.observe(2.0, op="save")
        h.observe(0.1, op="restore")
        assert h.count(op="save") == 2
        assert h.count(op="restore") == 1
        assert h.cumulative_counts(op="save") == (1, 2)


class TestHistogramQuantile:
    """ISSUE-12 satellite: bucket-interpolated ``Histogram.quantile``
    (exact at bucket edges, documented one-bucket error bound, the same
    NaN/Inf guard family as ``observe``)."""

    def test_exact_at_bucket_edges(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0, 2.0, 4.0))
        for v in (1.0, 1.0, 2.0, 2.0):
            h.observe(v)
        # rank coincides with a cumulative count -> exactly the edge
        assert h.quantile(0.5) == 1.0
        assert h.quantile(1.0) == 2.0

    def test_interpolates_within_bucket(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0, 2.0))
        for _ in range(4):
            h.observe(1.5)                # all in (1.0, 2.0]
        # rank q*4=2 of 4 -> halfway through the bucket's count
        assert h.quantile(0.5) == pytest.approx(1.5)
        assert h.quantile(0.25) == pytest.approx(1.25)
        # error bound: any estimate stays inside the populated bucket
        for q in (0.01, 0.5, 0.99):
            assert 1.0 <= h.quantile(q) <= 2.0

    def test_first_bucket_lower_edge_is_zero(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0, 2.0))
        h.observe(0.5)
        h.observe(0.5)
        assert h.quantile(0.5) == pytest.approx(0.5)   # 0 + 1.0 * 1/2
        assert h.quantile(0.0) == 0.0

    def test_overflow_bucket_clamps_to_last_edge(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0, 2.0))
        h.observe(100.0)
        assert h.quantile(0.99) == 2.0
        assert h.quantile(0.0) == 2.0     # only populated "bucket"

    def test_single_bucket_histogram(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0,))
        h.observe(0.25)
        h.observe(0.75)
        assert h.quantile(0.5) == pytest.approx(0.5)
        assert h.quantile(1.0) == 1.0
        assert h.quantile(0.0) == 0.0

    def test_empty_histogram_is_nan(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0,))
        assert math.isnan(h.quantile(0.5))

    def test_q_guards_match_observe_family(self, reg):
        h = reg.histogram("apex_t_q_seconds", buckets=(1.0,))
        h.observe(0.5)
        for bad in (-0.01, 1.01, float("nan"), float("inf"),
                    -float("inf")):
            with pytest.raises(ValueError, match="quantile"):
                h.quantile(bad)

    def test_labeled_series_quantiles_independent(self, reg):
        h = reg.histogram("apex_t_q_seconds", "h", ("op",),
                          buckets=(1.0, 2.0, 4.0))
        h.observe(0.5, op="a")
        h.observe(3.0, op="b")
        assert h.quantile(0.5, op="a") <= 1.0
        assert h.quantile(0.5, op="b") > 2.0
        assert math.isnan(h.quantile(0.5, op="c")
                          ) if h.count(op="c") == 0 else True

    def test_monotone_in_q(self, reg):
        h = reg.histogram("apex_t_q_seconds",
                          buckets=tuple(float(b) for b in
                                        (1, 2, 4, 8, 16)))
        rng = np.random.default_rng(0)
        for v in rng.uniform(0.1, 20.0, 200):
            h.observe(float(v))
        qs = [h.quantile(q) for q in np.linspace(0, 1, 21)]
        assert qs == sorted(qs)


class TestConcurrency:
    N_THREADS, N_OPS = 8, 5_000

    def test_concurrent_updates_are_exact(self, reg):
        c = reg.counter("apex_t_total", "h", ("t",))
        h = reg.histogram("apex_t_lat_seconds", "h", buckets=(0.5,))
        g = reg.gauge("apex_t_depth")

        def worker(tid):
            for i in range(self.N_OPS):
                c.inc(t=str(tid % 2))
                h.observe(0.25 if i % 2 else 0.75)
                g.inc()

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = self.N_THREADS * self.N_OPS
        assert c.value(t="0") + c.value(t="1") == total
        assert h.count() == total
        assert h.cumulative_counts() == (total // 2, total)
        assert g.value() == total

    def test_exposition_during_concurrent_writes(self, reg):
        c = reg.counter("apex_t_total")
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                c.inc()

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(50):
                text = reg.prometheus_text()
                assert "apex_t_total" in text
        finally:
            stop.set()
            t.join()


class TestExposition:
    def test_prometheus_golden_text(self, reg):
        c = reg.counter("apex_g_total", "help text", ("kind",))
        c.inc(kind="a")
        c.inc(2, kind="b")
        g = reg.gauge("apex_g_depth", "queue depth")
        g.set(3)
        h = reg.histogram("apex_g_lat_seconds", "latency",
                          buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(5.0)
        h.observe(50.0)
        assert reg.prometheus_text() == (
            '# HELP apex_g_depth queue depth\n'
            '# TYPE apex_g_depth gauge\n'
            'apex_g_depth 3\n'
            '# HELP apex_g_lat_seconds latency\n'
            '# TYPE apex_g_lat_seconds histogram\n'
            'apex_g_lat_seconds_bucket{le="1"} 1\n'
            'apex_g_lat_seconds_bucket{le="10"} 2\n'
            'apex_g_lat_seconds_bucket{le="+Inf"} 3\n'
            'apex_g_lat_seconds_sum 55.5\n'
            'apex_g_lat_seconds_count 3\n'
            '# HELP apex_g_total help text\n'
            '# TYPE apex_g_total counter\n'
            'apex_g_total{kind="a"} 1\n'
            'apex_g_total{kind="b"} 2\n')

    def test_label_values_are_escaped(self, reg):
        c = reg.counter("apex_g_total", "", ("what",))
        c.inc(what='a"b\\c\nd')
        assert r'what="a\"b\\c\nd"' in reg.prometheus_text()

    def test_json_export_is_atomic_and_loadable(self, reg, tmp_path):
        c = reg.counter("apex_g_total")
        c.inc(7)
        path = str(tmp_path / "metrics.json")
        reg.write_json(path)
        with open(path) as f:
            payload = json.load(f)
        assert payload["metrics"]["apex_g_total"]["series"] == [
            {"labels": {}, "value": 7.0}]
        assert payload["time"] > 0
        # no temp litter left behind
        assert [p.name for p in tmp_path.iterdir()] == ["metrics.json"]

    def test_reset_zeroes_series_keeps_registrations(self, reg):
        c = reg.counter("apex_g_total", "h", ("k",))
        c.inc(k="a")
        g = reg.gauge("apex_g_depth")
        g.set_function(lambda: 9.0)
        reg.reset()
        assert c.value(k="a") == 0.0
        assert reg.counter("apex_g_total", "h", ("k",)) is c
        # bound functions describe live state: they survive reset
        assert g.value() == 9.0


# --------------------------------------------------------------------------
# spans -> Chrome trace JSON
# --------------------------------------------------------------------------

class TestSpans:
    def test_no_recorder_is_a_noop(self):
        assert trace.uninstall_recorder() is None or True  # park any
        with trace.span("free") as s:
            assert s is None
            assert trace.current_span() is None

    def test_nesting_parentage_and_containment(self):
        with trace.recording() as rec:
            with trace.span("outer", step=3) as outer:
                assert trace.current_span() is outer
                with trace.span("inner_a") as inner:
                    assert inner.parent_id == outer.span_id
                with trace.span("inner_b"):
                    pass
            assert trace.current_span() is None
        payload = rec.to_chrome_trace()
        # schema: loads as JSON, every event is a complete "X" event
        loaded = json.loads(json.dumps(payload))
        evs = loaded["traceEvents"]
        assert [e["name"] for e in evs] == ["outer", "inner_a", "inner_b"]
        for e in evs:
            assert e["ph"] == "X"
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            assert e["dur"] >= 0.0
        o, a, b = evs
        assert "parent_id" not in o["args"] and o["args"]["step"] == 3
        assert a["args"]["parent_id"] == o["args"]["span_id"]
        assert b["args"]["parent_id"] == o["args"]["span_id"]
        # proper nesting: children inside the parent window, in order
        assert o["ts"] <= a["ts"] and a["ts"] + a["dur"] <= o["ts"] + o["dur"]
        assert a["ts"] + a["dur"] <= b["ts"]
        assert b["ts"] + b["dur"] <= o["ts"] + o["dur"]

    def test_span_survives_exceptions_and_still_records(self):
        with trace.recording() as rec:
            with pytest.raises(RuntimeError):
                with trace.span("doomed"):
                    raise RuntimeError("body failed")
            assert trace.current_span() is None
        assert [e["name"] for e in rec.to_chrome_trace()["traceEvents"]] \
            == ["doomed"]

    def test_attributes_and_events(self):
        with trace.recording() as rec:
            with trace.span("op", a=1) as s:
                s.set_attribute("b", "two")
                s.add_event("milestone", detail=7)
        [ev] = rec.to_chrome_trace()["traceEvents"]
        assert ev["args"]["a"] == 1 and ev["args"]["b"] == "two"
        [stamped] = ev["args"]["events"]
        assert stamped["name"] == "milestone" and stamped["detail"] == 7
        assert ev["ts"] <= stamped["ts_us"] <= ev["ts"] + ev["dur"]

    def test_threads_get_independent_span_stacks(self):
        seen = {}

        def worker():
            with trace.span("thread_side") as s:
                seen["parent"] = s.parent_id

        with trace.recording() as rec:
            with trace.span("main_side"):
                t = threading.Thread(target=worker)
                t.start()
                t.join()
        assert seen["parent"] is None  # no cross-thread parentage
        tids = {e["tid"] for e in rec.to_chrome_trace()["traceEvents"]}
        assert len(tids) == 2

    def test_export_writes_loadable_file(self, tmp_path):
        with trace.recording() as rec:
            with trace.span("op"):
                pass
        path = str(tmp_path / "trace.json")
        rec.export(path)
        with open(path) as f:
            loaded = json.load(f)
        assert loaded["traceEvents"][0]["name"] == "op"
        assert loaded["displayTimeUnit"] == "ms"

    def test_export_stays_strict_json_under_nan_attributes(self, tmp_path):
        with trace.recording() as rec:
            # NaN at top level, nested in a tuple (json serializes
            # tuples natively — the finite-walk must reach inside), and
            # a non-JSON object (degrades via default=str)
            with trace.span("diverged", loss=float("nan"),
                            grads=(float("nan"), 1.0),
                            arr=np.ones(2)) as s:
                s.add_event("blowup", delta=float("inf"))
        path = str(tmp_path / "trace.json")
        rec.export(path)
        with open(path) as f:
            loaded = json.load(f, parse_constant=_reject_constant)
        [ev] = loaded["traceEvents"]
        assert ev["args"]["loss"] is None
        assert ev["args"]["grads"] == [None, 1.0]
        assert isinstance(ev["args"]["arr"], str)
        assert ev["args"]["events"][0]["delta"] is None

    def test_recorder_caps_events_and_reports_drops(self):
        rec = trace.TraceRecorder(max_events=2)
        prev = trace.uninstall_recorder()
        trace.install_recorder(rec)
        try:
            for i in range(5):
                with trace.span("s", i=i):
                    pass
        finally:
            trace.uninstall_recorder()
            if prev is not None:
                trace.install_recorder(prev)
        assert len(rec) == 2 and rec.dropped == 3
        payload = rec.to_chrome_trace()
        # the run's BEGINNING is kept, and truncation is never silent
        assert [e["args"]["i"] for e in payload["traceEvents"]] == [0, 1]
        assert payload["otherData"] == {"dropped_events": 3,
                                        "max_events": 2}
        with pytest.raises(ValueError):
            trace.TraceRecorder(max_events=0)

    def test_recording_restores_previous_recorder(self):
        outer = trace.install_recorder()
        try:
            with trace.recording() as inner:
                with trace.span("in_window"):
                    pass
            with trace.span("after_window"):
                pass
            assert [e["name"] for e in
                    inner.to_chrome_trace()["traceEvents"]] == ["in_window"]
            assert [e["name"] for e in
                    outer.to_chrome_trace()["traceEvents"]] \
                == ["after_window"]
        finally:
            trace.uninstall_recorder()

    def test_jax_profiler_hooks_are_idempotent(self, tmp_path):
        logdir = str(tmp_path / "prof")
        # ONE profiler session covers the whole contract: the on_stall
        # adapter starts it, re-entry is refused while active, stop is
        # idempotent (start/stop cycles cost seconds on this backend)
        hook = trace.profile_on_stall(logdir)
        hook({"step": 3})
        if not trace._PROFILER_ACTIVE:
            pytest.skip("jax profiler unavailable on this backend")
        try:
            assert trace.start_jax_profiler(logdir) is False  # already on
            hook({"step": 4})  # second stall: no double start, no raise
        finally:
            assert trace.stop_jax_profiler() is True
        assert trace.stop_jax_profiler() is False  # already off


# --------------------------------------------------------------------------
# emit_event sink registry + the event -> metric bridge
# --------------------------------------------------------------------------

class TestSinkRegistry:
    def test_default_output_is_byte_identical_json(self, events):
        returned = _logging.emit_event("obs_test_probe", step=3,
                                       note="hello")
        [line] = [e for e in events()
                  if e["event"] == "obs_test_probe"]
        # the logged line parses back to exactly the returned event, and
        # the raw message is exactly the canonical dumps — the pre-PR
        # format, byte for byte
        assert line == json.loads(
            json.dumps(returned, sort_keys=True, default=str))

    def test_raw_line_matches_canonical_dumps(self):
        records = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r.getMessage())
        logger = logging.getLogger("apex_tpu.events")
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        try:
            returned = _logging.emit_event("obs_test_probe", a=1)
        finally:
            logger.removeHandler(handler)
        assert records == [
            json.dumps(returned, sort_keys=True, default=str)]

    def test_custom_sink_receives_every_event(self, events):
        _logging.emit_event("obs_test_probe", n=1)
        _logging.emit_event("obs_test_probe", n=2)
        mine = [e for e in events.sunk if e["event"] == "obs_test_probe"]
        assert [e["n"] for e in mine] == [1, 2]

    def test_add_is_idempotent_and_remove_unsubscribes(self):
        seen = []
        before = len(_logging.event_sinks())
        _logging.add_event_sink(seen.append)
        _logging.add_event_sink(seen.append)
        assert len(_logging.event_sinks()) == before + 1
        _logging.emit_event("obs_test_probe")
        _logging.remove_event_sink(seen.append)
        _logging.remove_event_sink(seen.append)  # no-op, no raise
        _logging.emit_event("obs_test_probe")
        assert len(seen) == 1

    def test_raising_sink_never_breaks_the_emitter(self, events):
        def bad_sink(event):
            raise RuntimeError("sink bug")

        _logging.add_event_sink(bad_sink)
        try:
            out = _logging.emit_event("obs_test_probe", n=3)
        finally:
            _logging.remove_event_sink(bad_sink)
        assert out["n"] == 3
        # the default log sink still ran
        assert [e["n"] for e in events("obs_test_probe")] == [3]

    def test_rank_info_warned_set_is_capped(self):
        saved = set(_logging._RANK_INFO_WARNED)
        _logging._RANK_INFO_WARNED.clear()
        try:
            for i in range(3 * _logging._MAX_WARNED_KEYS):
                _logging._debug_once(f"obs_cap_probe_{i}", "probe",
                                     ValueError("x"))
            assert len(_logging._RANK_INFO_WARNED) \
                == _logging._MAX_WARNED_KEYS
        finally:
            _logging._RANK_INFO_WARNED.clear()
            _logging._RANK_INFO_WARNED.update(saved)


class TestBridge:
    def test_bridge_is_installed_by_default(self):
        assert bridge.installed()

    def test_every_event_kind_is_counted(self):
        REGISTRY.reset()
        _logging.emit_event("obs_test_probe")
        _logging.emit_event("obs_test_probe")
        _logging.emit_event("obs_other_probe")
        assert bridge.EVENTS_TOTAL.value(event="obs_test_probe") == 2
        assert bridge.EVENTS_TOTAL.value(event="obs_other_probe") == 1

    def test_payload_handlers_map_measurements(self):
        REGISTRY.reset()
        _logging.emit_event("retry_attempt", what="data_fetch")
        _logging.emit_event("retry_exhausted", what="ckpt_save")
        _logging.emit_event("batch_skipped", reasons=["nan"])
        _logging.emit_event("replica_desync", leaf="w")
        _logging.emit_event("fault_injected", fault="slow_step")
        _logging.emit_event("serving_first_token", rid="r", ttft_s=0.02)
        _logging.emit_event("serving_request_finished", rid="r",
                            tokens_per_s=123.0, per_token_ms=2.0)
        assert bridge.RETRY_ATTEMPTS.value(what="data_fetch") == 1
        assert bridge.RETRY_EXHAUSTED.value(what="ckpt_save") == 1
        assert bridge.BATCHES_SKIPPED.value() == 1
        assert bridge.REPLICA_DESYNC.value() == 1
        assert bridge.FAULTS_INJECTED.value(fault="slow_step") == 1
        assert bridge.SERVING_TTFT.count() == 1
        assert bridge.SERVING_TTFT.sum() == pytest.approx(0.02)
        assert bridge.SERVING_PER_TOKEN.sum() == pytest.approx(0.002)
        assert bridge.SERVING_TOKENS_PER_S.value() == 123.0

    def test_malformed_serving_events_are_skipped_not_zeroed(self):
        """A serving event missing its measurement field must not land
        a fabricated 0.0 sample in the latency histograms."""
        REGISTRY.reset()
        _logging.emit_event("serving_first_token", rid="r")  # no ttft_s
        _logging.emit_event("serving_request_finished", rid="r",
                            per_token_ms="not-a-number")
        assert bridge.SERVING_TTFT.count() == 0
        assert bridge.SERVING_PER_TOKEN.count() == 0
        # the event itself is still counted
        assert bridge.EVENTS_TOTAL.value(
            event="serving_first_token") == 1

    def test_events_stamp_the_active_span(self):
        with trace.recording() as rec:
            with trace.span("op"):
                _logging.emit_event("obs_test_probe", n=1)
        [ev] = rec.to_chrome_trace()["traceEvents"]
        assert [s["name"] for s in ev["args"]["events"]] \
            == ["obs_test_probe"]

    def test_uninstall_stops_feeding_reinstall_resumes(self):
        REGISTRY.reset()
        bridge.uninstall()
        try:
            _logging.emit_event("obs_test_probe")
            assert bridge.EVENTS_TOTAL.value(event="obs_test_probe") == 0
        finally:
            bridge.install()
        _logging.emit_event("obs_test_probe")
        assert bridge.EVENTS_TOTAL.value(event="obs_test_probe") == 1


# --------------------------------------------------------------------------
# instrumented subsystems
# --------------------------------------------------------------------------

class TestInstrumentedPieces:
    def test_checkpoint_durations_by_op(self, tmp_path):
        REGISTRY.reset()
        hist = REGISTRY.get("apex_checkpoint_duration_seconds")
        tree = {"w": jnp.arange(8.0)}
        path = rz.save_checkpoint(str(tmp_path), 0, tree)
        rz.validate_checkpoint(path)
        rz.restore_checkpoint(str(tmp_path), like=tree)
        assert hist.count(op="save") == 1
        # restore fuses validation, so only the explicit call counts
        assert hist.count(op="validate") == 1
        assert hist.count(op="restore") == 1
        assert hist.sum(op="save") > 0.0

    def test_sharded_checkpoint_durations_are_observed(self, tmp_path,
                                                       mesh8):
        """The v2 (elastic) manager path feeds the SAME duration series
        as v1 — the docs' unqualified save/validate/restore inventory
        row holds for both formats."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from apex_tpu.resilience import elastic as el

        REGISTRY.reset()
        hist = REGISTRY.get("apex_checkpoint_duration_seconds")
        state = {"b": jax.device_put(
            jnp.ones((8,)), NamedSharding(mesh8, P("dp")))}
        el.save_sharded_checkpoint(str(tmp_path), 0, state, mesh=mesh8)
        el.restore_sharded_checkpoint(str(tmp_path), state)
        assert hist.count(op="save") == 1
        assert hist.count(op="restore") == 1

    def test_failed_restore_is_not_observed(self, tmp_path):
        REGISTRY.reset()
        hist = REGISTRY.get("apex_checkpoint_duration_seconds")
        with pytest.raises(rz.CheckpointError):
            rz.restore_checkpoint(str(tmp_path / "empty"), like={})
        assert hist.count(op="restore") == 0

    def test_timers_publish_as_gauge_series(self):
        timers = Timers()
        with timers("fwd").timing():
            time.sleep(0.01)
        with timers("bwd").timing():
            pass
        snap = timers.publish_metrics()
        assert set(snap) == {"fwd", "bwd"}
        assert bridge.TIMER_SECONDS.value(region="fwd") \
            == snap["fwd"]["total_s"]
        assert bridge.TIMER_SECONDS.value(region="fwd") >= 0.01
        assert 'apex_timer_seconds{region="fwd"}' \
            in metrics.prometheus_text()

    def test_heartbeat_age_gauge_reads_at_scrape_time(self):
        gauge = REGISTRY.get("apex_heartbeat_age_seconds")
        gauge.set_function(None)  # isolate from earlier-suite watchdogs
        clock = _FakeClock()
        wd = rz.StepWatchdog(deadline_s=100.0, poll_interval_s=50.0,
                             clock=clock)
        # constructing must NOT touch the gauge (a prepared-but-idle
        # watchdog would otherwise shadow a healthy running one)
        assert gauge.bound_function() is None
        wd.start()
        assert gauge.value() == -1.0  # never beaten
        wd.beat(0)
        clock.t += 7.5
        assert gauge.value() == 7.5  # age grows without new samples
        wd.beat(1)
        assert gauge.value() == 0.0
        # stop() releases the binding: a finished run must not report a
        # forever-growing age (false wedged-host signal) — but the
        # series stays present, pushed to the honest -1 sentinel
        wd.stop()
        assert gauge.bound_function() is None
        assert gauge.value() == -1.0

    def test_reused_supervisor_keeps_heartbeat_gauge(self):
        """run() -> stop() releases the gauge; a second run()'s start()
        re-acquires it — a reused supervisor never loses its probe."""
        gauge = REGISTRY.get("apex_heartbeat_age_seconds")
        gauge.set_function(None)
        sup = rz.TrainingSupervisor(None, rz.SupervisorConfig(
            step_deadline_s=30.0, poll_interval_s=5.0))
        bound_mid_run = []

        def step_fn(state, batch, step):
            bound_mid_run.append(gauge.bound_function() is not None)
            return state

        sup.run(step_fn, None, iter(range(2)), num_steps=2)
        assert gauge.bound_function() is None  # released with run 1
        sup.run(step_fn, None, iter(range(2)), num_steps=2)
        assert bound_mid_run == [True] * 4
        assert gauge.bound_function() is None

    def test_watchdog_gauge_binding_nests_and_survives_misorder(self):
        gauge = REGISTRY.get("apex_heartbeat_age_seconds")
        gauge.set_function(None)
        outer = rz.StepWatchdog(deadline_s=100.0,
                                poll_interval_s=50.0).start()
        inner = rz.StepWatchdog(deadline_s=100.0,
                                poll_interval_s=50.0).start()
        # a short-lived inner watchdog hands the gauge BACK to the
        # still-running outer one instead of clearing it
        inner.stop()
        assert gauge.bound_function() == outer._beat_age
        outer.stop()
        assert gauge.bound_function() is None
        # misordered stops: the displaced watchdog's stop is a no-op,
        # and when the survivor stops, the resurrected released binding
        # reports the honest -1 sentinel, never a frozen growing age
        a = rz.StepWatchdog(deadline_s=100.0, poll_interval_s=50.0).start()
        a.beat(0)
        b = rz.StepWatchdog(deadline_s=100.0, poll_interval_s=50.0).start()
        a.stop()
        assert gauge.bound_function() == b._beat_age  # b still owns it
        b.stop()
        assert gauge.bound_function() == a._beat_age  # handed back...
        assert gauge.value() == -1.0  # ...but a is released: sentinel

    def test_engine_rejects_zero_slots(self):
        from apex_tpu.serving import DecodeEngine

        with pytest.raises(ValueError, match="slots"):
            DecodeEngine(object(), {}, slots=0, max_len=16, prefill_len=8)

    def test_engine_cache_utilization(self, engine):
        assert engine.cache_utilization() == 0.0
        engine.prefill(0, [1, 2, 3])
        assert engine.cache_utilization() == pytest.approx(3 / (2 * 16))
        engine.release(0)
        assert engine.cache_utilization() == 0.0


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def engine():
    """ONE engine (and one set of prefill/decode compiles) shared by
    every serving-side test in this module; each consumer starts from a
    reset cache.  Compile count stays exactly 1 by construction — which
    the acceptance run asserts through the decode-compiles gauge."""
    import jax

    from apex_tpu.models import LlamaConfig, LlamaForCausalLM
    from apex_tpu.serving import DecodeEngine

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=32)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    eng = DecodeEngine(model, params, slots=2, max_len=16, prefill_len=8)
    yield eng
    eng.reset()


# --------------------------------------------------------------------------
# ACCEPTANCE 1: fault-injected supervisor run -> exact counters + trace
# --------------------------------------------------------------------------

def test_acceptance_supervised_run_metrics_match_injected_faults(
        tmp_path, events):
    n_steps = 6
    flaky_failures = 2
    batches = [{"x": np.full((2, 3), float(i), np.float32)}
               for i in range(n_steps)]
    stream = rz.GuardedIterator(
        rz.CorruptBatch(
            rz.FlakyIterator(iter(batches), fail_at=(1,),
                             failures=flaky_failures),
            at=(3,), mode="nan", seed=7),
        spec=rz.spec_of(batches[0]), skip_budget=2)
    # isolate the heartbeat gauge from any unstopped earlier watchdog so
    # the released-at-stop assertion below sees this run's binding only
    REGISTRY.get("apex_heartbeat_age_seconds").set_function(None)
    mgr = rz.CheckpointManager(str(tmp_path), keep=n_steps)
    sup = rz.TrainingSupervisor(
        mgr,
        rz.SupervisorConfig(
            step_deadline_s=30.0, poll_interval_s=5.0, checkpoint_every=2,
            retry=rz.RetryPolicy(max_attempts=4, base_delay_s=0.0)),
        sleep=lambda s: None)

    gauge_seen = {}

    def step_fn(state, batch, step):
        if step == 3:  # mid-run: the heartbeat-age gauge is live
            gauge_seen["age"] = REGISTRY.get(
                "apex_heartbeat_age_seconds").value()
        return {"w": state["w"] + batch["x"].sum()}

    REGISTRY.reset()
    with trace.recording() as rec:
        state, last = sup.run(step_fn, {"w": np.float32(0.0)}, stream,
                              num_steps=n_steps)
    assert last == n_steps - 1

    # ---- counters exactly match the injected faults
    assert bridge.RETRY_ATTEMPTS.value(what="data_fetch") == flaky_failures
    assert bridge.EVENTS_TOTAL.value(event="retry_recovered") == 1
    assert bridge.BATCHES_SKIPPED.value() == 1
    assert bridge.EVENTS_TOTAL.value(event="batch_skipped") == 1
    assert bridge.FAULTS_INJECTED.value(fault="flaky_iterator") \
        == flaky_failures
    assert bridge.FAULTS_INJECTED.value(fault="corrupt_batch") == 1
    assert REGISTRY.get("apex_supervisor_steps_total").value() == n_steps
    step_hist = REGISTRY.get("apex_step_duration_seconds")
    assert step_hist.count() == n_steps
    # checkpoint_every=2 over 6 steps -> saves after steps 1, 3, 5
    ckpt_hist = REGISTRY.get("apex_checkpoint_duration_seconds")
    assert ckpt_hist.count(op="save") == 3
    assert bridge.EVENTS_TOTAL.value(event="checkpoint_saved") == 3

    # ---- the Prometheus snapshot carries those counts verbatim
    text = metrics.prometheus_text()
    assert 'apex_retry_attempts_total{what="data_fetch"} 2' in text
    assert 'apex_batches_skipped_total 1' in text
    assert 'apex_supervisor_steps_total 6' in text
    assert 'apex_events_total{event="checkpoint_saved"} 3' in text
    assert 'apex_step_duration_seconds_count 6' in text

    # ---- the Chrome trace loads and its spans line up with the run
    payload = json.loads(json.dumps(rec.to_chrome_trace()))
    evs = payload["traceEvents"]
    sup_spans = [e for e in evs if e["name"] == "supervisor_step"]
    steps = [e for e in evs if e["name"] == "train_step"]
    saves = [e for e in evs if e["name"] == "checkpoint_save"]
    assert [e["args"]["step"] for e in sup_spans] == list(range(n_steps))
    assert [e["args"]["step"] for e in steps] == list(range(n_steps))
    assert len(saves) == 3
    for e in evs:
        assert e["ph"] == "X" and e["dur"] >= 0.0
    # spans never overlap out of order: starts are non-decreasing
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    # proper nesting: every train_step / checkpoint_save is a child of
    # its step's supervisor_step span
    by_id = {e["args"]["span_id"]: e for e in evs}
    for child in steps + saves:
        assert by_id[child["args"]["parent_id"]]["name"] \
            == "supervisor_step"
    # the causal story rides the step span: the flaky fetch's retries
    # stamp step 1, the corrupt batch's skip stamps step 3, and each
    # save event stamps its own checkpoint_save span
    stamped_1 = [s["name"]
                 for s in sup_spans[1]["args"].get("events", [])]
    assert stamped_1.count("retry_attempt") == flaky_failures
    assert "retry_recovered" in stamped_1
    assert "batch_skipped" in [
        s["name"] for s in sup_spans[3]["args"].get("events", [])]
    assert all("checkpoint_saved" in
               [s["name"] for s in e["args"].get("events", [])]
               for e in saves)

    # ---- heartbeat age gauge: live mid-run, released at watchdog stop
    assert gauge_seen["age"] >= 0.0
    assert REGISTRY.get(
        "apex_heartbeat_age_seconds").bound_function() is None


# --------------------------------------------------------------------------
# ACCEPTANCE 2: continuous-batching drain -> exact counters + live gauges
# --------------------------------------------------------------------------

def test_acceptance_serving_drain_metrics_match_request_counts(events,
                                                               engine):
    from apex_tpu.serving import ContinuousBatchingScheduler, Request

    eng = engine
    eng.reset()
    sched = ContinuousBatchingScheduler(eng, max_queue=8, log_interval=1)
    n_requests, new_tokens = 4, 3

    REGISTRY.reset()
    for i in range(n_requests):
        sched.submit(Request(f"r{i}", [1 + i, 2, 3],
                             max_new_tokens=new_tokens))
    results = sched.run()
    assert len(results) == n_requests
    assert all(len(r.tokens) == new_tokens for r in results.values())

    # ---- counters exactly match the request counts
    for kind in ("serving_request_queued", "serving_request_admitted",
                 "serving_first_token", "serving_request_finished"):
        assert bridge.EVENTS_TOTAL.value(event=kind) == n_requests, kind
    assert bridge.SERVING_TTFT.count() == n_requests
    assert bridge.SERVING_QUEUE_WAIT.count() == n_requests
    assert bridge.SERVING_PER_TOKEN.count() == n_requests
    assert bridge.SERVING_TOKENS_PER_S.value() > 0.0

    # ---- gauges describe the drained end state
    assert bridge.SERVING_QUEUE_DEPTH.value() == 0.0
    assert bridge.SERVING_SLOT_OCCUPANCY.value() == 0.0
    assert bridge.SERVING_CACHE_UTILIZATION.value() == 0.0
    assert bridge.SERVING_DECODE_COMPILES.value() == 1.0

    # ---- the serving_step sample carries occupancy + cache utilization
    # in the SAME event (no more inferring one from the other)
    samples = events("serving_step")
    assert samples, "log_interval=1 must emit a sample every step"
    for s in samples:
        assert 0.0 <= s["slot_occupancy"] <= 1.0
        assert 0.0 <= s["cache_utilization"] <= 1.0
        assert s["active_slots"] <= eng.slots
    assert any(s["slot_occupancy"] == 1.0 for s in samples)  # both busy
    assert any(s["cache_utilization"] > 0.0 for s in samples)

    # ---- Prometheus snapshot carries the exact totals
    text = metrics.prometheus_text()
    assert ('apex_events_total{event="serving_request_finished"} 4'
            in text)
    assert 'apex_serving_ttft_seconds_count 4' in text
    assert 'apex_serving_queue_depth 0' in text


# --------------------------------------------------------------------------
# overhead: instrumentation must be negligible with no exporter attached
# --------------------------------------------------------------------------

def test_instrumented_step_overhead_is_bounded():
    """Full per-step instrumentation (span with no recorder + histogram
    observe + counter inc) on a ~100 µs CPU step must stay within a
    small multiple of the bare step.  Best-of-5 timings to shrug off
    scheduler noise; at ~7 µs of measured instrumentation the 3x bar
    leaves ~30x headroom against the ~100 µs step."""
    reg = MetricsRegistry()
    hist = reg.histogram("apex_t_step_seconds", "t")
    ctr = reg.counter("apex_t_steps_total", "t")
    a = np.ones((128, 128), np.float64)
    prev = trace.uninstall_recorder()  # measure the true default path
    try:
        def bare(n):
            t0 = time.perf_counter()
            for _ in range(n):
                (a @ a).sum()
            return time.perf_counter() - t0

        def instrumented(n):
            t0 = time.perf_counter()
            for _ in range(n):
                ts = time.perf_counter()
                with trace.span("step"):
                    (a @ a).sum()
                hist.observe(time.perf_counter() - ts)
                ctr.inc()
            return time.perf_counter() - t0

        n = 200
        bare(n), instrumented(n)  # warm caches
        t_bare = min(bare(n) for _ in range(5))
        t_inst = min(instrumented(n) for _ in range(5))
    finally:
        if prev is not None:
            trace.install_recorder(prev)
    assert ctr.value() == 6 * n
    assert t_inst <= 3.0 * t_bare, (
        f"instrumented {t_inst:.4f}s vs bare {t_bare:.4f}s "
        f"({t_inst / t_bare:.2f}x > 3x budget)")
