"""Edge cases of the DP-sharded batch samplers (ISSUE 2 satellite).

The samplers feed the data path the supervisor guards; their wraparound,
``drop_last``, and invalid-argument behavior was previously untested
robustness surface (`transformer/_data/_batchsampler.py`).
"""

import numpy as np
import pytest

from apex_tpu.transformer._data._batchsampler import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)


class TestSequentialSampler:
    def test_basic_sharding(self):
        # 8 samples, mbs 2, dp 2: each global batch of 4 is split by rank
        r0 = list(MegatronPretrainingSampler(8, 0, 2, 0, 2))
        r1 = list(MegatronPretrainingSampler(8, 0, 2, 1, 2))
        assert r0 == [[0, 1], [4, 5]]
        assert r1 == [[2, 3], [6, 7]]

    def test_consumed_resumes_mid_stream(self):
        got = list(MegatronPretrainingSampler(8, 4, 2, 0, 1))
        assert got == [[4, 5], [6, 7]]

    def test_consumed_at_total_yields_nothing(self):
        """Wraparound edge: consumed_samples == total_samples is a
        completed pass — the iterator is empty, not an error."""
        assert list(MegatronPretrainingSampler(8, 8, 2, 0, 1)) == []

    def test_consumed_beyond_total_yields_nothing(self):
        assert list(MegatronPretrainingSampler(8, 12, 2, 0, 1)) == []

    def test_drop_last_true_drops_ragged_tail(self):
        # 10 samples, global batch 4: the 2-sample tail vanishes
        got = list(MegatronPretrainingSampler(10, 0, 4, 0, 1,
                                              drop_last=True))
        assert got == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_drop_last_false_yields_ragged_tail(self):
        got = list(MegatronPretrainingSampler(10, 0, 4, 0, 1,
                                              drop_last=False))
        assert got[-1] == [8, 9]
        assert got[:-1] == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_drop_last_false_tail_is_rank_sliced(self):
        """The ragged tail is sliced by the SAME rank window as full
        batches: rank 0 takes the head, a later rank whose window lies
        beyond the tail gets an empty batch (reference parity — the
        consumer must tolerate it)."""
        r0 = list(MegatronPretrainingSampler(10, 8, 2, 0, 2,
                                             drop_last=False))
        r1 = list(MegatronPretrainingSampler(10, 8, 2, 1, 2,
                                             drop_last=False))
        assert r0 == [[8, 9]]
        assert r1 == [[]]

    @pytest.mark.parametrize("kwargs,match", [
        (dict(total_samples=0), "no sample to consume"),
        (dict(total_samples=-3), "no sample to consume"),
        (dict(micro_batch_size=0), "micro_batch_size"),
        (dict(data_parallel_size=0), "data parallel size"),
        (dict(data_parallel_rank=2), "smaller than data size"),
        (dict(data_parallel_rank=5, data_parallel_size=2),
         "smaller than data size"),
    ])
    def test_invalid_arguments_raise_runtime_error(self, kwargs, match):
        base = dict(total_samples=8, consumed_samples=0, micro_batch_size=2,
                    data_parallel_rank=0, data_parallel_size=2)
        base.update(kwargs)
        with pytest.raises(RuntimeError, match=match):
            MegatronPretrainingSampler(**base)


class TestRandomSampler:
    def test_epoch_covers_bucket_exactly_once(self):
        s = MegatronPretrainingRandomSampler(8, 0, 2, 0, 1)
        batches = list(s)
        assert all(len(b) == 2 for b in batches)
        assert sorted(i for b in batches for i in b) == list(range(8))

    def test_wraparound_reshuffles_next_epoch(self):
        """consumed_samples >= active total wraps into epoch 1: same
        index set, deterministic but different order than epoch 0."""
        epoch0 = [i for b in MegatronPretrainingRandomSampler(8, 0, 2, 0, 1)
                  for i in b]
        epoch1 = [i for b in MegatronPretrainingRandomSampler(8, 8, 2, 0, 1)
                  for i in b]
        again = [i for b in MegatronPretrainingRandomSampler(8, 8, 2, 0, 1)
                 for i in b]
        assert sorted(epoch0) == sorted(epoch1) == list(range(8))
        assert epoch1 == again            # deterministic per epoch
        assert epoch0 != epoch1           # epoch seeds the shuffle

    def test_mid_epoch_resume_skips_consumed(self):
        full = [i for b in MegatronPretrainingRandomSampler(8, 0, 2, 0, 1)
                for i in b]
        resumed = [i for b in MegatronPretrainingRandomSampler(8, 4, 2, 0, 1)
                   for i in b]
        assert resumed == full[4:]  # same permutation, offset past consumed

    def test_rank_buckets_are_disjoint(self):
        r0 = {i for b in MegatronPretrainingRandomSampler(16, 0, 2, 0, 2)
              for i in b}
        r1 = {i for b in MegatronPretrainingRandomSampler(16, 0, 2, 1, 2)
              for i in b}
        assert r0.isdisjoint(r1)
        assert sorted(r0 | r1) == list(range(16))

    def test_consumed_not_multiple_of_global_batch_asserts(self):
        s = MegatronPretrainingRandomSampler(8, 3, 2, 0, 1)
        with pytest.raises(AssertionError):
            iter(s).__next__()

    def test_ragged_total_drops_last_batch_size(self):
        """total % global-batch leftover is excluded from every epoch
        (last_batch_size semantics): 10 % 4 = 2 indices never appear."""
        s = MegatronPretrainingRandomSampler(10, 0, 4, 0, 1)
        seen = [i for b in s for i in b]
        assert len(seen) == 8
        assert set(seen) <= set(range(8))  # bucket excludes the ragged tail

    def test_invalid_arguments_raise_runtime_error(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingRandomSampler(0, 0, 2, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingRandomSampler(8, 0, 2, 3, 2)
