"""Tensor-parallel serving: DecodeEngine sharded over a tp mesh (ISSUE 15).

THE acceptance run: the greedy token stream of a ``tp=TPConfig(size=2)``
engine is **identical, token for token**, to the single-chip engine's
stream on the same prompt — prefill, decode, speculation-verify,
preempt/resume, prefix caching and paged CoW all running through
``shard_map``-wrapped versions of the very same jitted program bodies,
with every program family compiling exactly as often as the single-chip
engine.  Logits agree to float tolerance only (argmax-tier): the tp
forward reduces each layer's attention/MLP output with a ``psum`` whose
summation order differs from the single-chip matmul's, so f32 bytes
drift ~1e-7 while the argmax — and therefore the served stream — never
moves.  Cross-engine *cache bytes* inherit the same drift past layer 0
(hidden states carry it into K/V), which is why preemption parity is
asserted as within-engine bit-exactness plus cross-engine allclose,
never cross-engine byte equality.

Plus: weights restore directly onto the serving mesh for v1 and v2
checkpoint formats (no host-replicated detour), the default-off
identity guarantee (``tp`` unset ⇒ event stream and metric snapshot
exactly match the pre-tp engine), and divisibility validation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs import bridge as obs_bridge
from apex_tpu.serving.engine import TPConfig, tp_param_shardings
from apex_tpu.serving.paged_kv_cache import PagedCacheConfig
from apex_tpu.utils.compat import SERVING_TP_AXIS, serving_mesh

# GQA on purpose, like test_serving.py: kv_heads (2) < heads (4), so
# tp=2 splits the grouped-broadcast cache down to one kv head per rank
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
# tp=4 needs kv_heads % 4 == 0: MHA variant (every tp-sharded dim /4)
CFG_MHA = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def _prompt(seed=0, n=12):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(1, CFG.vocab_size, n)]


def _greedy(eng, prompt, steps, slot=0):
    """Greedy stream: prefill logits + ``steps`` decode argmaxes."""
    logits = eng.prefill(slot, list(prompt))
    stream = [int(jnp.argmax(logits))]
    toks = np.zeros((eng.slots,), np.int32)
    act = np.zeros((eng.slots,), bool)
    act[slot] = True
    for _ in range(steps):
        toks[slot] = stream[-1]
        logits = eng.decode(toks, act)[slot]
        stream.append(int(jnp.argmax(logits)))
    return stream, np.asarray(logits)


class _EventTap:
    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


# ---------------------------------------------------------------------------
# THE acceptance run: tp=2 / tp=4 greedy streams match single-chip
# ---------------------------------------------------------------------------


def test_tp2_greedy_stream_identical_to_single_chip(model, params):
    ref = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16)
    tp2 = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, tp=TPConfig(size=2))
    assert tp2.tp_size == 2 and tp2.mesh is not None
    s_ref, l_ref = _greedy(ref, _prompt(), steps=24)
    s_tp, l_tp = _greedy(tp2, _prompt(), steps=24)
    # the served stream — the thing a client sees — is identical
    assert s_ref == s_tp
    # logits are argmax-tier: psum reduction order differs from the
    # single-chip matmul's, moving f32 bytes ~1e-7 but never the argmax
    np.testing.assert_allclose(l_tp, l_ref, rtol=1e-5, atol=1e-5)
    # same compile discipline as the single-chip engine
    assert tp2.decode_compiles() == 1
    assert tp2.prefill_compiles() == ref.prefill_compiles()


def test_tp4_greedy_stream_identical_mha(params):
    model4 = LlamaForCausalLM(CFG_MHA)
    p4 = model4.init(jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32))
    ref = sv.DecodeEngine(model4, p4, slots=1, max_len=64,
                          prefill_len=16)
    tp4 = sv.DecodeEngine(model4, p4, slots=1, max_len=64,
                          prefill_len=16, tp=TPConfig(size=4))
    s_ref, _ = _greedy(ref, _prompt(seed=4), steps=12)
    s_tp, _ = _greedy(tp4, _prompt(seed=4), steps=12)
    assert s_ref == s_tp
    assert tp4.decode_compiles() == 1


def test_tp_validation():
    with pytest.raises(ValueError):
        TPConfig(size=0)
    model = LlamaForCausalLM(CFG)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    with pytest.raises(ValueError):      # kv_heads=2 not divisible by 4
        sv.DecodeEngine(model, params, slots=1, max_len=32,
                        prefill_len=8, tp=TPConfig(size=4))


# ---------------------------------------------------------------------------
# sharded speculation: verify parity
# ---------------------------------------------------------------------------


def test_tp_speculation_verify_parity(model, params):
    """verify_draft on the tp engine accepts exactly what the
    single-chip engine accepts (the vocab-sharded rows are all-gathered
    inside the program before the argmax, so acceptance is
    rank-identical), and the greedy row vector matches bit for bit."""
    prompt = _prompt(seed=7)
    # the true greedy continuation, from a throwaway single-chip run:
    # s[0..4] get replayed via prefill+decode below, s[5..] drafted
    oracle = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                             prefill_len=16)
    s, _ = _greedy(oracle, prompt, steps=12)

    ref = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=16)
    tp2 = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=16, tp=TPConfig(size=2))
    assert _greedy(ref, prompt, steps=4)[0] == s[:5]
    assert _greedy(tp2, prompt, steps=4)[0] == s[:5]
    # pending token s[4]; a fully correct draft accepts whole + bonus
    a_ref, g_ref, r_ref = ref.verify_draft(0, [s[4]] + s[5:8])
    a_tp, g_tp, r_tp = tp2.verify_draft(0, [s[4]] + s[5:8])
    assert a_ref == a_tp == 3
    assert int(g_tp[3]) == s[8]
    assert np.array_equal(np.asarray(g_ref), np.asarray(g_tp))
    np.testing.assert_allclose(np.asarray(r_tp), np.asarray(r_ref),
                               rtol=1e-5, atol=1e-5)
    # a corrupted mid-draft token: identical partial accept + rollback
    bad = [s[9], (s[10] + 1) % CFG.vocab_size, s[11]]
    a_ref, g_ref, _ = ref.verify_draft(0, [s[8]] + bad)
    a_tp, g_tp, _ = tp2.verify_draft(0, [s[8]] + bad)
    assert a_ref == a_tp == 1
    assert np.array_equal(np.asarray(g_ref), np.asarray(g_tp))
    assert tp2.verify_compiles() == ref.verify_compiles() == 1


# ---------------------------------------------------------------------------
# preempt/resume across the mesh
# ---------------------------------------------------------------------------


@pytest.mark.slow   # ~4 s: tier-1 keeps the dense/paged lossless
# preemption witnesses in test_serving_policy.py and tp2 greedy identity
def test_tp_preempt_resume_within_engine_bit_exact(model, params):
    """Lossless preemption on the sharded engine: capture → release →
    restore → resumed prefill → decode continues the stream exactly as
    if never interrupted.  Parity is asserted WITHIN the tp engine
    (bit-exact) and ACROSS engines as allclose: captured K/V bytes past
    layer 0 carry the psum reduction-order drift, so cross-engine byte
    equality is structurally impossible (and not what lossless
    preemption promises — the bytes restored are the bytes captured)."""
    prompt = _prompt(seed=9)
    tp2 = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=16, tp=TPConfig(size=2))
    uninterrupted, _ = _greedy(tp2, prompt, steps=10)

    # same engine, fresh run: stop after 4 steps, capture, evict, resume
    tp2.release(0)
    partial, _ = _greedy(tp2, prompt, steps=4)
    k, v, n = tp2.capture_slot(0)
    assert n == len(prompt) + 4          # prompt + decoded-and-committed
    tp2.release(0)
    tp2.restore_prefix(0, (k, v), n)
    # context so far = prompt + emitted tokens whose K/V are cached
    ctx = prompt + partial[:4]
    logits = tp2.prefill(0, ctx + [partial[4]], resume=n)
    resumed = [int(jnp.argmax(logits))]
    toks = np.zeros((1,), np.int32)
    act = np.ones((1,), bool)
    for _ in range(5):
        toks[0] = resumed[-1]
        resumed.append(int(jnp.argmax(tp2.decode(toks, act)[0])))
    assert partial[:5] + resumed == uninterrupted

    # cross-engine: same capture from a single-chip engine agrees to
    # float tolerance — never byte-for-byte (see docstring)
    ref = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=16)
    _greedy(ref, prompt, steps=4)
    k_ref, v_ref, n_ref = ref.capture_slot(0)
    assert n_ref == n
    np.testing.assert_allclose(k, k_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(v, v_ref, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# sharded prefix caching: scheduler hit/restore parity
# ---------------------------------------------------------------------------


@pytest.mark.slow   # ~6 s: tier-1 keeps the dense prefix-hit trajectory
# witness in test_serving_prefix.py and tp2 greedy stream identity
def test_tp_prefix_cache_hit_stream_parity(model, params):
    """The scheduler's prefix-cache path over a tp engine: the second
    request admits via a cache hit (capture gathered the sharded K/V,
    restore re-sharded it head-wise) and its stream equals both the
    cold tp run and the single-chip run, token for token."""
    shared = _prompt(seed=21, n=48)      # 3 whole 16-token blocks
    p1 = shared + _prompt(seed=22, n=4)
    p2 = shared + _prompt(seed=23, n=4)

    def run(tp, prefix_caching, tag):
        eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                              prefill_len=16, tp=tp)
        sched = sv.ContinuousBatchingScheduler(
            eng, log_interval=10 ** 9, prefix_caching=prefix_caching)
        for i, p in enumerate((p1, p2)):
            sched.submit(sv.Request(f"{tag}{i}", p, max_new_tokens=6))
        return eng, sched.run()

    with _EventTap() as tap:
        eng_tp, on = run(TPConfig(size=2), sv.PrefixCacheConfig(), "t")
    hits = tap.of("serving_prefix_hit")
    assert len(hits) == 1 and hits[0]["saved_tokens"] == 48
    _, cold = run(TPConfig(size=2), None, "c")
    _, ref = run(None, sv.PrefixCacheConfig(), "r")
    toks = lambda res: [r.tokens for r in res.values()]  # noqa: E731
    assert toks(on) == toks(cold) == toks(ref)
    # restore compiled (the hit really restored) within its bound
    assert 1 <= eng_tp.restore_compiles() <= len(eng_tp.prefill_buckets)
    assert eng_tp.decode_compiles() == 1


# ---------------------------------------------------------------------------
# paged + CoW, sharded
# ---------------------------------------------------------------------------


@pytest.mark.slow   # ~5 s: tier-1 keeps the CoW both-ways bit-isolation
# witness in test_serving_paged.py — this is its tp composition variant
def test_tp_paged_fork_cow_stream_parity(model, params):
    ref = sv.DecodeEngine(model, params, slots=4, max_len=MAX,
                          prefill_len=16,
                          paged=PagedCacheConfig(block_size=8))
    tp2 = sv.DecodeEngine(model, params, slots=4, max_len=MAX,
                          prefill_len=16,
                          paged=PagedCacheConfig(block_size=8),
                          tp=TPConfig(size=2))
    prompt = _prompt(seed=5)
    s_ref, _ = _greedy(ref, prompt, steps=8)
    s_tp, _ = _greedy(tp2, prompt, steps=8)
    assert s_ref == s_tp
    # fork slot 0 -> 1 (zero-copy refcounted share), then decode both:
    # the CoW copy runs sharded and the two diverging streams match the
    # single-chip engine's
    for eng in (ref, tp2):
        eng.fork_slot(0, 1)
    toks = np.zeros((4,), np.int32)
    act = np.zeros((4,), bool)
    act[0] = act[1] = True
    toks[0] = toks[1] = s_ref[-1]
    for _ in range(3):
        out_r = ref.decode(toks, act)
        out_t = tp2.decode(toks, act)
        for s in (0, 1):
            assert int(jnp.argmax(out_r[s])) == int(jnp.argmax(out_t[s]))
        toks[0] = int(jnp.argmax(out_r[0]))
        toks[1] = int(jnp.argmax(out_r[1]))
    assert tp2.decode_compiles() == 1
    assert tp2.cow_compiles() == ref.cow_compiles() == 1


# ---------------------------------------------------------------------------
# weights: restore directly onto the serving mesh (v1 + v2)
# ---------------------------------------------------------------------------


def _assert_on_mesh(got_params, mesh):
    from jax.sharding import NamedSharding

    want = tp_param_shardings(got_params, mesh)
    for (kp, leaf), (_, sh) in zip(
            jax.tree_util.tree_flatten_with_path(got_params)[0],
            jax.tree_util.tree_flatten_with_path(want)[0]):
        assert isinstance(leaf.sharding, NamedSharding), kp
        assert leaf.sharding.spec == sh.spec, (
            f"{jax.tree_util.keystr(kp)}: {leaf.sharding.spec} "
            f"!= {sh.spec}")


def test_tp_weights_restore_onto_mesh_v1(model, params, tmp_path):
    from apex_tpu.resilience import save_checkpoint

    state = {"params": params, "step": jnp.int32(7)}
    save_checkpoint(str(tmp_path), 7, state)
    mesh = serving_mesh(2)
    got, step = sv.load_serving_params(
        str(tmp_path), like=state, params_key="params",
        shardings=tp_param_shardings(params, mesh))
    assert step == 7
    _assert_on_mesh(got["params"], mesh)
    # restored-onto-mesh params serve: identical stream to host params
    tp2 = sv.DecodeEngine(model, got, slots=1, max_len=MAX,
                          prefill_len=16, tp=TPConfig(size=2))
    ref = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=16)
    s_tp, _ = _greedy(tp2, _prompt(seed=2), steps=6)
    s_ref, _ = _greedy(ref, _prompt(seed=2), steps=6)
    assert s_tp == s_ref


@pytest.mark.slow   # ~4 s: tier-1 keeps the v1-manifest witness of the
# same restore-onto-mesh claim
def test_tp_weights_restore_onto_mesh_v2(model, params, tmp_path):
    from jax.sharding import Mesh

    from apex_tpu.resilience import save_sharded_checkpoint

    save_mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",))
    state = {"params": params, "step": jnp.int32(3)}
    save_sharded_checkpoint(str(tmp_path), 3, state, mesh=save_mesh)
    mesh = serving_mesh(2)
    got, step = sv.load_serving_params(
        str(tmp_path), like=state, params_key="params",
        shardings=tp_param_shardings(params, mesh))
    assert step == 3
    _assert_on_mesh(got["params"], mesh)
    tp2 = sv.DecodeEngine(model, got, slots=1, max_len=MAX,
                          prefill_len=16, tp=TPConfig(size=2))
    s_tp, _ = _greedy(tp2, _prompt(seed=3), steps=4)
    ref = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=16)
    s_ref, _ = _greedy(ref, _prompt(seed=3), steps=4)
    assert s_tp == s_ref


# ---------------------------------------------------------------------------
# default-off identity + telemetry
# ---------------------------------------------------------------------------


def test_tp_default_off_identity(model, params):
    """``tp`` unset ⇒ today's engine exactly: no mesh, no serving_tp_step
    events, and the tp gauge/histogram untouched in the metric
    snapshot."""
    gauge0 = obs_bridge.SERVING_TP_SIZE.value()
    hist0 = obs_bridge.SERVING_COLLECTIVE_SECONDS.count()
    eng = sv.DecodeEngine(model, params, slots=1, max_len=32,
                          prefill_len=8)
    assert eng.tp is None and eng.tp_size == 1 and eng.mesh is None
    with _EventTap() as tap:
        _greedy(eng, _prompt(n=4), steps=3)
    assert tap.of("serving_tp_step") == []
    assert obs_bridge.SERVING_TP_SIZE.value() == gauge0
    assert obs_bridge.SERVING_COLLECTIVE_SECONDS.count() == hist0


def test_tp_step_events_feed_metrics(model, params):
    hist0 = obs_bridge.SERVING_COLLECTIVE_SECONDS.count()
    tp2 = sv.DecodeEngine(model, params, slots=1, max_len=32,
                          prefill_len=8, tp=TPConfig(size=2))
    assert tp2.tp == TPConfig(size=2)
    assert tp2.mesh.axis_names == (SERVING_TP_AXIS,)
    with _EventTap() as tap:
        _greedy(tp2, _prompt(n=4), steps=3)
    steps = tap.of("serving_tp_step")
    assert len(steps) == 3
    for e in steps:
        assert e["tp"] == 2 and e["active"] == 1
        assert e["duration_s"] > 0
    assert obs_bridge.SERVING_TP_SIZE.value() == 2
    assert obs_bridge.SERVING_COLLECTIVE_SECONDS.count() == hist0 + 3
