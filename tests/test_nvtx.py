"""nvtx-compat trace annotations + named_scope labels survive into HLO."""

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.utils import nvtx


def _hlo_with_labels(lowered):
    """Scope labels live in the lowering's debug info on jax >= 0.5
    (``as_text(debug_info=True)``); jax 0.4.x has no such kwarg and
    only surfaces them in the compiled HLO's metadata."""
    try:
        return lowered.as_text(debug_info=True)
    except TypeError:  # jax 0.4.x
        return lowered.compile().as_text()


def test_range_context_and_stack():
    with nvtx.range("outer"):
        depth = nvtx.range_push("inner")
        assert depth == 1
        assert nvtx.range_pop() == 1
    with pytest.raises(RuntimeError):
        nvtx.range_pop()


def test_named_scope_labels_reach_hlo():
    def fn(x):
        with nvtx.range("my_hot_region"):
            return jnp.sum(x * 2.0)

    hlo = _hlo_with_labels(jax.jit(fn).lower(jnp.ones((8,))))
    assert "my_hot_region" in hlo


def test_model_scopes_reach_hlo():
    from apex_tpu.transformer.testing import GPTModel

    model = GPTModel(num_layers=1, hidden_size=32, num_attention_heads=2,
                     vocab_size=64, max_sequence_length=16)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    hlo = _hlo_with_labels(jax.jit(lambda p, i: model.apply(p, i)).lower(
        params, ids))
    assert "parallel_attention" in hlo
    assert "parallel_mlp" in hlo
