"""contrib.openfold_triton: Evoformer attention core + FusedAdamSWA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.openfold_triton import (
    AdamMathType,
    CanSchTriMHA,
    FusedAdamSWA,
    LayerNormSmallShapeOptImpl,
    attention_core,
)
from apex_tpu.optimizers import FusedAdam


def test_attention_core_matches_reference_math():
    rng = np.random.default_rng(0)
    B, H, Q, K, D = 2, 4, 8, 8, 16
    q = jnp.asarray(rng.standard_normal((B, H, Q, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, K, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, K, D)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal((B, H, Q, K)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (B, 1, 1, K)), jnp.float32)

    got = attention_core(q, k, v, mask=mask, bias=bias)

    scores = np.einsum("bhqd,bhkd->bhqk", q, k) + np.asarray(bias)
    scores = np.where(np.asarray(mask).astype(bool), scores, -1e9)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", probs, np.asarray(v))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert CanSchTriMHA([1, 256, 4, 256, 16])


def test_attention_core_grads_flow():
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 2, 4, 8)), jnp.float32)
    g = jax.grad(lambda q: attention_core(q, q, q).sum())(q)
    assert np.all(np.isfinite(np.asarray(g)))


def test_layer_norm_small_shape():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((6, 3, 32)), jnp.float32)
    w, b = jnp.ones(32), jnp.zeros(32)
    y = LayerNormSmallShapeOptImpl.apply(x, (32,), w, b)
    np.testing.assert_allclose(np.asarray(y).mean(-1), 0.0, atol=1e-5)


@pytest.mark.parametrize("mode", [AdamMathType.ApexAdam,
                                  AdamMathType.ApexAdamW,
                                  AdamMathType.PyTorchAdam])
def test_adam_swa_math_modes(mode):
    rng = np.random.default_rng(3)
    params = {"w": jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)}
    grads = {"w": jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)}
    opt = FusedAdamSWA(lr=1e-2, weight_decay=0.01, adam_math_mode=mode,
                       swa_decay_rate=0.9)
    state = opt.init(params)
    p = params
    for _ in range(3):
        p, state = opt.step(grads, p, state)
    assert np.all(np.isfinite(np.asarray(p["w"])))
    assert int(state.n_averaged) == 3

    # ApexAdam/ApexAdamW modes pin the repo's FusedAdam math exactly
    if mode is not AdamMathType.PyTorchAdam:
        ref = FusedAdam(lr=1e-2, weight_decay=0.01,
                        adam_w_mode=(mode is AdamMathType.ApexAdamW))
        rp, rs = params, ref.init(params)
        for _ in range(3):
            rp, rs = ref.step(grads, rp, rs)
        np.testing.assert_allclose(p["w"], rp["w"], rtol=1e-5, atol=1e-6)


def test_bf16_params_accumulate_in_fp32_master():
    """Sub-bf16-resolution updates must not be lost (the reference's fp32
    state-params contract): many tiny steps still move the master."""
    p = {"w": jnp.full((4,), 100.0, jnp.bfloat16)}
    opt = FusedAdamSWA(lr=0.1, betas=(0.0, 0.0), eps=1.0,
                       bias_correction=False)
    state = opt.init(p)
    g = {"w": jnp.full((4,), 1e-3, jnp.float32)}
    for _ in range(5):
        p, state = opt.step(g, p, state)
    master = np.asarray(state.state_params["w"])
    # each ~1e-4 step is far below bf16 resolution at 100.0 (~0.5), so the
    # bf16 compute params stay put — but the fp32 master accumulates
    assert np.all(master < 100.0)
    assert np.all(np.asarray(p["w"], np.float32) == 100.0)
    assert np.all(np.isfinite(master))


def test_swa_average_tracks_params():
    rng = np.random.default_rng(4)
    params = {"w": jnp.asarray(rng.standard_normal((4, 4)), jnp.float32)}
    opt = FusedAdamSWA(lr=1e-1, swa_decay_rate=0.5)
    state = opt.init(params)
    p = params
    # first step: swa copies through the updated params (n_averaged == 0)
    g = {"w": jnp.ones((4, 4), jnp.float32)}
    p, state = opt.step(g, p, state)
    np.testing.assert_allclose(state.swa_params["w"], p["w"], rtol=1e-6)
    # then EMA: swa' = swa + 0.5 * (p - swa)
    prev_swa = state.swa_params["w"]
    p, state = opt.step(g, p, state)
    want = prev_swa + 0.5 * (p["w"] - prev_swa)
    np.testing.assert_allclose(state.swa_params["w"], want, rtol=1e-6)
