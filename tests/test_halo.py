"""Halo exchange + spatial parallelism on the 8-device CPU mesh.

The correctness bar (mirroring apex/contrib/bottleneck/test.py): a conv /
bottleneck computed on spatially-split shards with halo exchange must
equal the same op on the unsplit tensor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map

from apex_tpu.contrib.halo import (
    HaloExchanger1d,
    SpatialBottleneck,
    halo_exchange_1d,
    spatial_conv2d,
)


@pytest.fixture
def mesh4():
    return Mesh(np.array(jax.devices()[:4]), ("spatial",))


def test_halo_exchange_attaches_neighbor_rows(mesh4):
    # global H=8 split over 4 ranks, half_halo=1
    x = jnp.arange(8 * 3, dtype=jnp.float32).reshape(1, 8, 1, 3)

    def fn(shard):
        return halo_exchange_1d(shard, 1, "spatial", spatial_dim=1)

    with mesh4:
        out = jax.jit(shard_map(fn, mesh=mesh4, in_specs=P(None, "spatial"),
                                out_specs=P(None, "spatial"),
                                **NO_REP_CHECK))(x)
    out = np.asarray(out)  # [1, 4 ranks * 4 rows, 1, 3]
    x_np = np.asarray(x)
    # rank 1 holds global rows 2:4; with halo it sees rows 1:5
    rank1 = out[:, 4:8]
    np.testing.assert_array_equal(rank1[:, 1:3], x_np[:, 2:4])
    np.testing.assert_array_equal(rank1[:, 0], x_np[:, 1])
    np.testing.assert_array_equal(rank1[:, 3], x_np[:, 4])
    # rank 0's low halo is zero-filled (non-periodic line)
    np.testing.assert_array_equal(out[:, 0], np.zeros_like(x_np[:, 0]))


def test_spatial_conv_matches_unsplit(mesh4):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, 8, 4)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 4, 6)), jnp.float32)

    want = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def fn(shard):
        return spatial_conv2d(shard, w, "spatial")

    with mesh4:
        got = jax.jit(shard_map(fn, mesh=mesh4, in_specs=P(None, "spatial"),
                                out_specs=P(None, "spatial"),
                                **NO_REP_CHECK))(x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_halo_exchanger_object_form(mesh4):
    x = jnp.ones((1, 8, 2, 2), jnp.float32)
    ex = HaloExchanger1d("spatial", half_halo=2)

    def fn(shard):
        return ex(shard)

    with mesh4:
        out = jax.jit(shard_map(fn, mesh=mesh4, in_specs=P(None, "spatial"),
                                out_specs=P(None, "spatial"),
                                **NO_REP_CHECK))(x)
    assert out.shape == (1, 8 + 2 * 2 * 4, 2, 2)


def test_spatial_bottleneck_matches_dense(mesh4):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 16, 8, 8)), jnp.float32)

    dense = SpatialBottleneck(in_channels=8, bottleneck_channels=4,
                              out_channels=8, spatial_axis=None)
    params = dense.init(jax.random.PRNGKey(0), x)
    want = dense.apply(params, x)

    spatial = SpatialBottleneck(in_channels=8, bottleneck_channels=4,
                                out_channels=8)

    def fn(shard):
        return spatial.apply(params, shard)

    with mesh4:
        got = jax.jit(shard_map(fn, mesh=mesh4, in_specs=P(None, "spatial"),
                                out_specs=P(None, "spatial"),
                                **NO_REP_CHECK))(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_spatial_bottleneck_grads_flow_not_to_frozen_bn(mesh4):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((1, 8, 4, 8)), jnp.float32)
    m = SpatialBottleneck(in_channels=8, bottleneck_channels=4,
                          out_channels=8, spatial_axis=None)
    params = m.init(jax.random.PRNGKey(0), x)
    g = jax.grad(lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
    flat = jax.tree_util.tree_flatten_with_path(g)[0]
    for path, leaf in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if "_scale" in name or "_bias" in name:
            assert np.all(np.asarray(leaf) == 0), name  # frozen BN
        elif "conv" in name:
            assert np.abs(np.asarray(leaf)).max() > 0, name


def test_halo_validation():
    with pytest.raises(ValueError):
        # even kernel extent
        spatial_conv2d(jnp.zeros((1, 4, 4, 2)), jnp.zeros((2, 2, 2, 2)), "x")
