"""Cross-request prefix caching (ISSUE 10): reuse shared-prompt K/V
with bit-exact resume.

THE acceptance run: two requests sharing a 70+ token prefix — the
second admits via a cache hit, and its full logit trajectory (prefill
plus >= 20 greedy decode steps) is **bit-identical** to a cold-cache
run of the same prompt, with a neighbor slot mid-chunked-prefill
asserted bit-isolated throughout.  Eviction under a tight budget never
evicts a ref'd (pinned) entry, and a post-eviction miss falls back to
full prefill bit-identically.

Plus: `kv_cache` slot-region primitive edges (start=0, spans abutting
``max_len``, interaction with ``commit_slot_length`` on a full slot —
the rollback primitive PR 8 added), prefix-store unit semantics (chain
hashing, LRU leaf-first eviction, pinning, orphan refusal, span-shared
byte accounting), the hit/miss events + metrics wiring, and the
default-off identity witnesses (no prefix events, zero restore
compiles, unchanged program set).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.serving.kv_cache import (
    commit_slot_length,
    init_cache,
    read_slot_region,
    write_slot_region,
)
from apex_tpu.serving.prefix_cache import PrefixCache

# the serving suite's GQA config (kv_heads < heads)
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def _prompt(seed=0, n=5):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, CFG.vocab_size, n)]


class _EventTap:
    """Capture emit_event kinds (and payloads) for a with-block."""

    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def kinds(self):
        return [e.get("event") for e in self.events]

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


# ---------------------------------------------------------------------------
# kv_cache slot-region primitives: edges
# ---------------------------------------------------------------------------


def _region(seed, n):
    hd = CFG.hidden_size // CFG.num_attention_heads
    rng = np.random.default_rng(seed)
    shape = (CFG.num_hidden_layers, n, CFG.kv_heads, hd)
    return (jnp.asarray(rng.standard_normal(shape), jnp.float32),
            jnp.asarray(rng.standard_normal(shape), jnp.float32))


def test_slot_region_write_read_start_zero_roundtrip():
    cache = init_cache(CFG, slots=3, max_len=16)
    k, v = _region(0, 6)
    cache = write_slot_region(cache, slot=1, start=0, k_region=k,
                              v_region=v)
    rk, rv = read_slot_region(cache, 1, 0, 6)
    assert np.array_equal(np.asarray(rk), np.asarray(k))
    assert np.array_equal(np.asarray(rv), np.asarray(v))
    # neighbors and rows past the span untouched
    assert np.asarray(cache.k)[:, 0].sum() == 0
    assert np.asarray(cache.k)[:, 2].sum() == 0
    assert np.asarray(cache.k)[:, 1, 6:].sum() == 0
    # lengths untouched by design: the caller commits
    assert np.asarray(cache.lengths).tolist() == [0, 0, 0]


def test_slot_region_span_abutting_max_len():
    cache = init_cache(CFG, slots=2, max_len=16)
    k, v = _region(1, 4)
    cache = write_slot_region(cache, slot=0, start=12, k_region=k,
                              v_region=v)      # rows [12, 16): exact fit
    rk, _ = read_slot_region(cache, 0, 12, 16)
    assert np.array_equal(np.asarray(rk), np.asarray(k))
    # an overhanging span DROPS its out-of-range rows (mode="drop"),
    # never clamps the write backward onto earlier rows
    k2, v2 = _region(2, 4)
    cache2 = write_slot_region(cache, slot=0, start=14, k_region=k2,
                               v_region=v2)    # rows 14, 15 land; 16, 17 drop
    got = np.asarray(cache2.k)[:, 0]
    assert np.array_equal(got[:, 14:16], np.asarray(k2)[:, :2])
    # rows [12, 14) keep the FIRST write (no backward clamp)
    assert np.array_equal(got[:, 12:14], np.asarray(k)[:, :2])


def test_slot_region_with_commit_slot_length_on_full_slot():
    """Fill a slot to max_len, commit, roll back via commit_slot_length
    (the PR-8 rollback primitive), and overwrite the rolled-back span —
    region reads see exactly the committed truth at each stage."""
    cache = init_cache(CFG, slots=2, max_len=16)
    k, v = _region(3, 16)
    cache = write_slot_region(cache, slot=0, start=0, k_region=k,
                              v_region=v)
    cache = commit_slot_length(cache, 0, 16)          # full slot
    assert np.asarray(cache.lengths).tolist() == [16, 0]
    rk, _ = read_slot_region(cache, 0, 0, 16)         # whole-slot read
    assert np.array_equal(np.asarray(rk), np.asarray(k))
    # rollback: same O(1) move as speculative-verify rejection
    cache = commit_slot_length(cache, 0, 10)
    assert np.asarray(cache.lengths).tolist() == [10, 0]
    # the bytes past the rollback are still there (unreadable by the
    # masking contract, not erased) — and an overwrite replaces them
    k2, v2 = _region(4, 6)
    cache = write_slot_region(cache, slot=0, start=10, k_region=k2,
                              v_region=v2)
    cache = commit_slot_length(cache, 0, 16)
    rk2, _ = read_slot_region(cache, 0, 10, 16)
    assert np.array_equal(np.asarray(rk2), np.asarray(k2))
    rk3, _ = read_slot_region(cache, 0, 0, 10)        # prefix untouched
    assert np.array_equal(np.asarray(rk3), np.asarray(k)[:, :10])


def test_slot_region_validation():
    cache = init_cache(CFG, slots=1, max_len=8)
    with pytest.raises(ValueError):           # empty region
        read_slot_region(cache, 0, 4, 4)
    with pytest.raises(ValueError):
        read_slot_region(cache, 0, 5, 3)


# ---------------------------------------------------------------------------
# prefix store unit semantics (host-side, no model)
# ---------------------------------------------------------------------------


def test_prefix_cache_chain_hash_encodes_position():
    blk = tuple(range(16))
    h1 = PrefixCache.chain_hash(PrefixCache.ROOT, blk)
    h2 = PrefixCache.chain_hash(h1, blk)
    assert h1 != h2                  # same tokens, different position
    assert h1 == PrefixCache.chain_hash(PrefixCache.ROOT, list(blk))


def test_prefix_cache_match_caps_at_prompt_minus_one():
    pc = PrefixCache(block_size=4, max_tokens=1 << 20)
    prompt = list(range(12))
    h = PrefixCache.ROOT
    for i in range(3):
        k, v = _region(i, 4)
        e = pc.put(h, prompt[4 * i:4 * i + 4], k, v)
        h = e.chain
    # 12 cached tokens exist, but a 12-token prompt may only reuse 8:
    # the final token must be recomputed for the next-token logits
    covered, entries = pc.match(prompt)
    assert covered == 8 and len(entries) == 2
    covered, entries = pc.match(prompt + [99])   # 13 tokens: all 3 match
    assert covered == 12 and len(entries) == 3
    covered, entries = pc.match(prompt[:4])      # too short for a block
    assert covered == 0 and entries == []
    covered, _ = pc.match([7] * 12)              # different content
    assert covered == 0


def test_prefix_cache_lru_leaf_first_eviction_and_pinning():
    pc = PrefixCache(block_size=4, max_tokens=8)       # room for 2 blocks
    a = pc.put(PrefixCache.ROOT, [1, 2, 3, 4], *_region(0, 4))
    b = pc.put(a.chain, [5, 6, 7, 8], *_region(1, 4))
    assert pc.cached_tokens == 8
    # pin a only: inserting c must evict b (the oldest unpinned LEAF),
    # never a — a is pinned AND mid-chain while b lives
    pc.acquire([a])
    c = pc.put(PrefixCache.ROOT, [9, 9, 9, 9], *_region(2, 4))
    assert c is not None
    assert pc.cached_tokens == 8
    assert b.chain not in pc
    assert a.chain in pc and c.chain in pc
    # with everything else pinned, a fresh insert is itself the only
    # evictable entry: the budget holds, the pinned chain is untouched
    d = pc.put(c.chain, [8, 8, 8, 8], *_region(3, 4))
    assert pc.cached_tokens <= 12
    # release a: the next insert evicts LRU-first among unpinned leaves
    pc.release([a])
    e = pc.put(PrefixCache.ROOT, [3, 3, 3, 3], *_region(4, 4))
    assert e is not None and e.chain in pc
    assert a.chain not in pc          # unpinned now, oldest -> evicted
    assert pc.cached_tokens <= 8
    stats = pc.stats()
    assert stats["evicted"] >= 2 and stats["inserted"] == 5
    del d


def test_put_blocks_own_entries_survive_their_own_eviction_pass():
    """With every other entry pinned and the budget exhausted, an
    insert must NOT evict the blocks it just created before the caller
    can pin them: put_blocks' returned entries are guaranteed live
    (the pre-pin eviction window would hand back dead entries, kill
    the chain a live prefill is extending, and break the capture
    path's bounded-compile contract downstream)."""
    pc = PrefixCache(block_size=4, max_tokens=8)
    a = pc.put(PrefixCache.ROOT, [1, 2, 3, 4], *_region(0, 4))
    b = pc.put(a.chain, [5, 6, 7, 8], *_region(1, 4))
    pc.acquire([a, b])               # everything pinned, budget full
    k, v = _region(2, 8)
    c, d = pc.put_blocks(PrefixCache.ROOT, [[9, 9, 9, 9], [8, 8, 8, 8]],
                         k, v)
    assert c.chain in pc and d.chain in pc, (
        "fresh entries evicted by their own insert's budget pass")
    assert pc.cached_tokens == 16    # transiently over budget instead
    # once the caller pins them, a later unpinned insert is the one
    # that gets evicted (or itself refused room) — never the pinned
    pc.acquire([c, d])
    e = pc.put(PrefixCache.ROOT, [3, 3, 3, 3], *_region(3, 4))
    assert a.chain in pc and b.chain in pc
    assert c.chain in pc and d.chain in pc
    pc.release([a, b, c, d])
    del e


def test_prefill_resume_rejection_is_side_effect_free(model, params):
    """A rejected prefill(resume=...) must not consume the restore
    mark: the caller can retry with a corrected prompt instead of
    re-paying the whole device restore."""
    eng = sv.DecodeEngine(model, params, slots=1, max_len=32,
                          prefill_len=8)
    eng.prefill(0, _prompt(n=12))
    k, v = eng.read_region(0, 0, 8)
    eng.release(0)
    eng.restore_prefix(0, (k, v), 8)
    with pytest.raises(ValueError):       # prompt beyond cache capacity
        eng.prefill(0, _prompt(n=40), resume=8)
    # the restored state is intact — the corrected retry succeeds
    logits = eng.prefill(0, _prompt(n=12), resume=8)
    assert logits is not None and eng.lengths()[0] == 12


def test_prefix_cache_orphan_insert_refused_and_idempotence():
    pc = PrefixCache(block_size=4, max_tokens=1 << 20)
    gone = PrefixCache.chain_hash(PrefixCache.ROOT, (0, 0, 0, 0))
    assert pc.put(gone, [1, 1, 1, 1], *_region(0, 4)) is None
    assert pc.stats()["refused"] == 1
    a = pc.put(PrefixCache.ROOT, [1, 2, 3, 4], *_region(1, 4))
    again = pc.put(PrefixCache.ROOT, [1, 2, 3, 4], *_region(2, 4))
    assert again is a                 # idempotent: first capture wins
    assert pc.stats()["inserted"] == 1
    with pytest.raises(ValueError):   # release must pair with acquire
        pc.release([a])
    pc.acquire([a])
    with pytest.raises(ValueError):   # live pins block clear()
        pc.clear()
    pc.release([a])
    pc.clear()
    assert len(pc) == 0 and pc.cached_bytes == 0


def test_prefix_cache_span_sharing_and_byte_accounting():
    pc = PrefixCache(block_size=4, max_tokens=8)
    k, v = _region(0, 8)
    nbytes = k.nbytes + v.nbytes
    a, b = pc.put_blocks(PrefixCache.ROOT, [[1, 2, 3, 4], [5, 6, 7, 8]],
                         k, v)
    assert a.span is b.span and pc.cached_bytes == nbytes
    # gather of the whole span is the span arrays themselves (no slice)
    gk, gv = PrefixCache.gather_kv([a, b])
    assert gk is k and gv is v
    # a partial chain slices once
    gk2, _ = PrefixCache.gather_kv([a])
    assert np.array_equal(np.asarray(gk2), np.asarray(k)[:, :4])
    # evicting ONE block of the span frees no bytes (the span survives
    # for its sibling); evicting the last frees them all
    pc.put(PrefixCache.ROOT, [7, 7, 7, 7], *_region(1, 4))  # forces evict
    assert pc.cached_tokens == 8
    assert b.chain not in pc and a.chain in pc
    assert pc.cached_bytes == nbytes + _region(1, 4)[0].nbytes * 2
    pc.put(PrefixCache.ROOT, [6, 6, 6, 6], *_region(2, 4))
    assert a.chain not in pc
    assert pc.cached_bytes == _region(1, 4)[0].nbytes * 4


def test_prefix_cache_config_validation():
    with pytest.raises(ValueError):
        sv.PrefixCacheConfig(block_size=0)
    with pytest.raises(ValueError):
        sv.PrefixCacheConfig(max_tokens=0)
    with pytest.raises(ValueError):
        PrefixCache(block_size=4, max_tokens=8).put(
            PrefixCache.ROOT, [1, 2, 3], *_region(0, 3))  # partial block


# ---------------------------------------------------------------------------
# THE acceptance run: hit trajectory bit-identical, neighbor isolated
# ---------------------------------------------------------------------------


def test_prefix_hit_full_trajectory_bit_identical_with_neighbor(model,
                                                                params):
    """A 74-token prompt decodes cold; a second engine restores the
    70-token cached prefix (captured from the first), resumes prefill
    mid-prompt, and decodes 20 greedy steps — every f32 logit vector,
    prefill included, is bit-identical to the cold run, while a
    neighbor slot runs chunked prefill in the warm engine the whole
    time (bit-isolation both ways)."""
    prompt = _prompt(seed=11, n=74)
    neighbor_prompt = _prompt(seed=12, n=64)

    # cold reference: full prefill + 20 greedy steps, solo
    eng_cold = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                               prefill_len=16)
    logits = eng_cold.prefill(0, prompt)
    cold = [np.asarray(logits)]
    toks_cold = list(prompt)
    for _ in range(20):
        nxt = int(jnp.argmax(logits))
        toks_cold.append(nxt)
        logits = eng_cold.decode(np.array([nxt, 0], np.int32),
                                 np.array([True, False]))[0]
        cold.append(np.asarray(logits))

    # capture the first 70 tokens from the cold slot via the prefix
    # store (block 10 keeps 70 = 7 whole blocks)
    pc = PrefixCache(block_size=10, max_tokens=1 << 20)
    k, v = eng_cold.read_region(0, 0, 70)
    blocks = [prompt[i * 10:(i + 1) * 10] for i in range(7)]
    entries = pc.put_blocks(PrefixCache.ROOT, blocks, k, v)
    assert len(entries) == 7
    covered, chain = pc.match(prompt)
    assert covered == 70 and len(chain) == 7

    # warm engine: restore + resume, with the neighbor mid-prefill
    eng_warm = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                               prefill_len=16)
    eng_warm.prefill_chunk(1, neighbor_prompt[:16])    # neighbor starts
    eng_warm.restore_prefix(0, PrefixCache.gather_kv(chain), covered)
    assert eng_warm.lengths()[0] == 70
    logits = eng_warm.prefill(0, prompt, resume=70)
    assert np.array_equal(np.asarray(logits), cold[0]), (
        "resumed prefill diverged from the cold prefill")
    toks = list(prompt)
    for t in range(20):
        if t < 3:                                       # neighbor chunks
            eng_warm.prefill_chunk(
                1, neighbor_prompt[16 * (t + 1):16 * (t + 2)])
        nxt = int(jnp.argmax(logits))
        toks.append(nxt)
        logits = eng_warm.decode(np.array([nxt, 0], np.int32),
                                 np.array([True, False]))[0]
        assert np.array_equal(np.asarray(logits), cold[t + 1]), (
            f"warm decode diverged from cold at step {t}")
    assert toks == toks_cold
    # ... and the neighbor the warm engine prefilled next door equals
    # an isolated prefill of the same prompt, bit for bit
    eng_solo = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                               prefill_len=16)
    want = eng_solo.prefill(0, neighbor_prompt)
    got = eng_warm.prefill_chunk(1, neighbor_prompt[64:]) \
        if len(neighbor_prompt) > 64 else None
    # neighbor_prompt is exactly 64 tokens = 4 chunks, already complete
    assert got is None
    nk, _ = eng_warm.read_region(1, 0, 64)
    sk, _ = eng_solo.read_region(0, 0, 64)
    assert np.array_equal(np.asarray(nk), np.asarray(sk))
    del want
    # compile-count guards: restore bounded by the bucket table, the
    # decode step untouched
    assert eng_warm.restore_compiles() <= len(eng_warm.prefill_buckets)
    assert eng_warm.decode_compiles() == 1
    assert eng_cold.restore_compiles() == 0


def test_scheduler_hit_streams_and_telemetry(model, params):
    """Scheduler route of the acceptance claim: the second request
    admits via a cache hit (event + counters + saved-tokens histogram
    + cached-tokens gauge), prefill spends budget only on the suffix,
    and the hit stream equals a cold-scheduler run token for token."""
    from apex_tpu.obs import bridge as obs_bridge

    shared = _prompt(seed=21, n=72)
    p1 = shared + _prompt(seed=22, n=4)
    p2 = shared + _prompt(seed=23, n=4)

    def run(prefix_caching, rid_tag):
        eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                              prefill_len=16)
        sched = sv.ContinuousBatchingScheduler(
            eng, log_interval=10 ** 9, prefix_caching=prefix_caching)
        for i, p in enumerate((p1, p2)):
            sched.submit(sv.Request(f"{rid_tag}{i}", p,
                                    max_new_tokens=8))
        return sched, sched.run()

    hits0 = obs_bridge.SERVING_PREFIX_HITS.value()
    misses0 = obs_bridge.SERVING_PREFIX_MISSES.value()
    saved0 = obs_bridge.SERVING_PREFIX_SAVED.count()
    with _EventTap() as tap:
        sched_on, on = run(sv.PrefixCacheConfig(), "on")
    _, off = run(None, "off")
    assert [r.tokens for r in on.values()] \
        == [r.tokens for r in off.values()]
    # r0 missed (cold), r1 hit the 64 tokens of whole shared blocks
    assert len(tap.of("serving_prefix_miss")) == 1
    hits = tap.of("serving_prefix_hit")
    assert len(hits) == 1
    assert hits[0]["rid"] == "on1"
    assert hits[0]["saved_tokens"] == 64      # 4 x 16-token blocks <= 71
    # the suffix is the only prefill the hit paid: its chunk events
    # start at offset 64
    chunk_offsets = [e["offset_tokens"] for e in
                     tap.of("serving_prefill_chunk")
                     if e["rid"] == "on1"]
    assert chunk_offsets and min(chunk_offsets) == 64
    # metrics wiring (global registry: compare deltas)
    assert obs_bridge.SERVING_PREFIX_HITS.value() == hits0 + 1
    assert obs_bridge.SERVING_PREFIX_MISSES.value() == misses0 + 1
    assert obs_bridge.SERVING_PREFIX_SAVED.count() == saved0 + 1
    assert obs_bridge.SERVING_PREFIX_CACHED_TOKENS.value() \
        == sched_on.prefix_cache.cached_tokens
    assert sched_on.prefix_cache.stats()["hits"] == 1


def test_eviction_never_touches_pinned_and_miss_falls_back(model, params):
    """Under a tight budget, a request mid-chunked-prefill keeps its
    chain pinned across steps while another stream's capture forces
    eviction — the pinned entries survive, the OTHER chain is evicted,
    and a later admission of the evicted prompt misses and re-prefills
    to the exact cold-run stream."""
    pa = _prompt(seed=31, n=48)     # 3 x 16-token blocks
    pb = _prompt(seed=32, n=48)

    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16)
    sched = sv.ContinuousBatchingScheduler(
        eng, log_interval=10 ** 9, prefill_budget=16,
        prefix_caching=sv.PrefixCacheConfig(max_tokens=48))
    pc = sched.prefix_cache

    # A's prompt populates the cache (3 blocks = the whole budget)
    sched.submit(sv.Request("a", pa, max_new_tokens=2))
    res_a = sched.run()["a"]
    assert pc.cached_tokens == 48
    cov_a, _ = pc.match(pa + [0])
    assert cov_a == 48

    # B admits and prefills one 16-token chunk per step (budget 16);
    # its captures push the store over budget every step WHILE B's own
    # chain is pinned — eviction must consume A's released chain only
    sched.submit(sv.Request("b", pb, max_new_tokens=2))
    sched.step()
    pinned = [e for e in pc._entries.values() if e.refs]
    assert len(pinned) == 1          # B's first block, mid-prefill pin
    assert pc.cached_tokens > 0
    sched.run()
    cov_b, _ = pc.match(pb + [0])
    assert cov_b == 48               # B's chain intact (was pinned)
    cov_a2, _ = pc.match(pa + [0])
    assert cov_a2 < 48               # A's chain (partially) evicted
    assert pc.stats()["evicted"] >= 1
    assert not [e for e in pc._entries.values() if e.refs]  # all released

    # post-eviction: A's prompt misses (or partially hits) and the
    # stream still equals the original cold stream bit-for-bit at the
    # token level
    with _EventTap() as tap:
        sched.submit(sv.Request("a2", pa, max_new_tokens=2))
        res_a2 = sched.run()["a2"]
    assert res_a2.tokens == res_a.tokens
    assert (len(tap.of("serving_prefix_miss"))
            + len(tap.of("serving_prefix_hit"))) == 1


# ---------------------------------------------------------------------------
# default-off identity + guards
# ---------------------------------------------------------------------------


def test_prefix_caching_off_leaves_serving_path_untouched(model, params):
    """The default (no ``prefix_caching``) must not change a byte:
    no prefix events, no restore/read compiles, the same program set —
    and the scheduler signature stays backward compatible."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16)
    sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9)
    assert sched.prefix_cache is None
    with _EventTap() as tap:
        sched.submit(sv.Request("r", _prompt(seed=41, n=40),
                                max_new_tokens=4))
        sched.run()
    kinds = set(tap.kinds())
    assert not any("prefix" in str(k) for k in kinds)
    assert kinds <= {"serving_request_queued", "serving_request_admitted",
                     "serving_prefill_chunk", "serving_first_token",
                     "serving_request_finished", "serving_step"}
    assert eng.restore_compiles() == 0
    assert eng.prefill_compiles() <= len(eng.prefill_buckets)
    assert eng.decode_compiles() == 1


def test_restore_and_resume_guards(model, params):
    eng = sv.DecodeEngine(model, params, slots=2, max_len=32,
                          prefill_len=8)
    eng.prefill(0, _prompt(n=12))
    k, v = eng.read_region(0, 0, 8)
    with pytest.raises(ValueError):           # read past valid length
        eng.read_region(0, 8, 16)
    with pytest.raises(ValueError):           # restore into occupied slot
        eng.restore_prefix(0, (k, v), 8)
    with pytest.raises(ValueError):           # resume without restore
        eng.prefill(1, _prompt(n=12), resume=8)
    with pytest.raises(ValueError):           # shape mismatch
        eng.restore_prefix(1, (k[:1], v[:1]), 8)
    with pytest.raises(ValueError):           # more rows than provided
        eng.restore_prefix(1, (k, v), 9)
    with pytest.raises(ValueError):           # full-cache restore
        big = jnp.zeros((CFG.num_hidden_layers, 32, CFG.kv_heads,
                         CFG.hidden_size // CFG.num_attention_heads))
        eng.restore_prefix(1, (big, big), 32)
    eng.restore_prefix(1, (k, v), 8)
    with pytest.raises(ValueError):           # resume offset mismatch
        eng.prefill(1, _prompt(n=12), resume=4)
    with pytest.raises(ValueError):           # no suffix to compute
        eng.prefill(1, _prompt(n=8), resume=8)
    # release clears the restored mark
    eng.release(1)
    eng.prefill_chunk(1, [1, 2])              # plain continue still fine
    with pytest.raises(ValueError):
        eng.prefill(1, _prompt(n=12), resume=8)
    # scheduler-level: a block that cannot fit beside the resume token
    with pytest.raises(ValueError):
        sv.ContinuousBatchingScheduler(
            eng, prefix_caching=sv.PrefixCacheConfig(block_size=32))
