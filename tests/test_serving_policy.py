"""Serving control plane (ISSUE 13): lossless priority preemption,
cancellation, deadline shedding, tenant fairness — and chaos.

THE acceptance run: a 2x-overload bursty open-loop workload with mixed
priorities, deadlines, and injected slow decode steps, driven on a
virtual clock.  Every surviving stream's tokens are bit-identical to an
unperturbed isolated run, preempted streams resume losslessly (the
engine-level twin pins exact f32 logits across the preempt/resume
boundary), and the policy run's high-priority p99 TTFT and goodput are
strictly better than the FIFO scheduler on the *same* workload with
the *same* chaos.

Default-off identity: a scheduler without ``policy=`` run over
policy-annotated requests produces the event stream and serving-metric
snapshot of a plain FIFO run, exactly.  No new compiled programs on
the policy path: preempt/resume rides the existing region-read /
restore / alias program families (compile counts asserted).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging, obs
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs import request_trace as rt
from apex_tpu.obs import slo as oslo
from apex_tpu.resilience.fault_injection import (
    CancelStorm,
    SlowDecodeStep,
    StallStream,
)

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


@pytest.fixture(scope="module")
def _engine_mod(model, params):
    return sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                           prefill_len=32)


@pytest.fixture
def engine(_engine_mod):
    """Shared 2-slot dense engine, reset per test — fresh engines are
    reserved for tests that assert per-engine compile counts (every
    jit family recompiles per engine, ~seconds each on CPU)."""
    _engine_mod.reset()
    return _engine_mod


@pytest.fixture(scope="module")
def _eng1_mod(model, params):
    return sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=32)


@pytest.fixture
def eng1(_eng1_mod):
    """Shared single-slot dense engine, reset per test."""
    _eng1_mod.reset()
    return _eng1_mod


def _prompt(seed, n=8):
    return [int(x)
            for x in np.random.default_rng(seed).integers(0, 128, n)]


def _mk_engine(model, params, *, slots=2, paged=False, num_blocks=None):
    return sv.DecodeEngine(
        model, params, slots=slots, max_len=MAX, prefill_len=32,
        paged=(sv.PagedCacheConfig(block_size=16, num_blocks=num_blocks)
               if paged else None))


@pytest.fixture(scope="module")
def isolated_tokens(_eng1_mod):
    """``fn(request) -> tokens``: the request's stream run alone on a
    FIFO scheduler — the unperturbed reference every chaos survivor
    must match bit for bit.  The shared single-slot engine (compiled
    once) + a generation-config memo keep the many reference runs
    cheap."""
    eng = _eng1_mod
    memo = {}

    def run(request):
        key = (tuple(request.prompt), request.max_new_tokens,
               request.eos_id, request.temperature, request.top_k,
               request.seed)
        if key not in memo:
            eng.reset()
            sched = sv.ContinuousBatchingScheduler(eng, max_queue=4)
            sched.submit(sv.Request("ref", request.prompt,
                                    max_new_tokens=request.max_new_tokens,
                                    eos_id=request.eos_id,
                                    temperature=request.temperature,
                                    top_k=request.top_k,
                                    seed=request.seed))
            memo[key] = sched.run()["ref"].tokens
        return memo[key]

    return run


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------


class TestPolicyUnits:
    def test_policy_validation(self):
        with pytest.raises(ValueError, match="weights must be > 0"):
            sv.SchedulingPolicy(tenant_weights={"a": 0.0})
        with pytest.raises(ValueError, match="default_tenant_weight"):
            sv.SchedulingPolicy(default_tenant_weight=-1.0)
        with pytest.raises(ValueError, match="max_inflight_per_tenant"):
            sv.SchedulingPolicy(max_inflight_per_tenant=0)
        pol = sv.SchedulingPolicy(tenant_weights={"paid": 3.0})
        assert pol.weight_of("paid") == 3.0
        assert pol.weight_of("anyone_else") == 1.0

    def test_wrr_smooth_proportions_and_determinism(self):
        pol = sv.SchedulingPolicy(tenant_weights={"a": 3.0, "b": 1.0})

        def picks(n):
            wrr = sv.WeightedRoundRobin(pol)
            return [wrr.pick(["a", "b"]) for _ in range(n)]

        seq = picks(8)
        assert seq == picks(8)                     # deterministic
        assert seq.count("a") == 6 and seq.count("b") == 2   # 3:1
        # smooth: "b" is interleaved, not parked at the tail
        assert "b" in seq[:4] and "b" in seq[4:]

    def test_wrr_snapshot_restore_and_starvation_credit(self):
        pol = sv.SchedulingPolicy()
        wrr = sv.WeightedRoundRobin(pol)
        assert wrr.pick([]) is None
        snap = wrr.snapshot()
        first = wrr.pick(["a", "b"])
        wrr.restore(snap)
        assert wrr.pick(["a", "b"]) == first       # rollback is exact
        # a tenant kept ineligible accrues credit and wins on re-entry
        for _ in range(3):
            wrr.pick(["a"])
        wrr._credit["b"] = 5.0                     # earned while waiting
        assert wrr.pick(["a", "b"]) == "b"

    def test_request_control_fields_validated_at_submit(self, engine):
        sched = sv.ContinuousBatchingScheduler(engine, max_queue=4)
        with pytest.raises(ValueError, match="deadline_s"):
            sched.submit(sv.Request("d", [1, 2], max_new_tokens=1,
                                    deadline_s=0.0))
        with pytest.raises(ValueError, match="tenant"):
            sched.submit(sv.Request("t", [1, 2], max_new_tokens=1,
                                    tenant=""))


# ---------------------------------------------------------------------------
# lossless capture/restore: exact f32 logits across the boundary
# ---------------------------------------------------------------------------


class TestEngineCapture:
    def test_capture_restore_exact_logits_across_boundary(self, model,
                                                          params):
        """Prefill + 3 decodes, capture, release, restore into a
        DIFFERENT slot, 3 more decodes: every post-boundary f32 logits
        row equals the uninterrupted run bit for bit — the
        lossless-preemption exactness witness."""
        prompt = _prompt(5, 20)
        eng = _mk_engine(model, params, slots=2)

        def drive(interrupt):
            eng.reset()
            logits = eng.prefill(0, prompt)
            toks = [int(np.argmax(np.asarray(logits)))]
            rows = []
            slot = 0
            for i in range(6):
                if interrupt and i == 3:
                    k, v, n = eng.capture_slot(slot)
                    assert n == len(prompt) + len(toks) - 1
                    eng.release(slot)
                    slot = 1
                    eng.restore_prefix(slot, (k, v), n)
                tok = np.zeros((2,), np.int32)
                act = np.zeros((2,), bool)
                tok[slot] = toks[-1]
                act[slot] = True
                lg = np.asarray(eng.decode(tok, act)[slot])
                rows.append(lg)
                toks.append(int(np.argmax(lg)))
            return toks, rows

        ref_toks, ref_rows = drive(interrupt=False)
        got_toks, got_rows = drive(interrupt=True)
        assert got_toks == ref_toks
        for a, b in zip(ref_rows, got_rows):
            assert (a == b).all()          # exact f32, not allclose

    def test_capture_guards_and_compile_bound(self, model, params):
        eng = _mk_engine(model, params, slots=2)
        with pytest.raises(ValueError, match="empty"):
            eng.capture_slot(0)
        assert eng.capture_compiles() == 0     # nothing read yet
        # every capture length decomposes over the bucket table: the
        # read program family stays bounded by len(buckets) plus
        # sub-floor whole-slot extents
        # sub-floor whole slot (3), exact bucket (16), sub-floor tail
        # (20 = 16 + overlap), multi-bucket with tail (50 = 32+16+ovl)
        for n in (3, 16, 20, 50):
            eng.reset()
            eng.prefill(0, _prompt(n, n))
            for _ in range(3):
                eng.decode(np.array([0, 0], np.int32),
                           np.array([True, False]))
            k, v, length = eng.capture_slot(0)
            assert length == n + 3
            assert k.shape[1] == length == v.shape[1]
        bound = len(eng.prefill_buckets) + eng.prefill_buckets[0] - 1
        assert 1 <= eng.capture_compiles() <= bound
        paged = _mk_engine(model, params, paged=True)
        with pytest.raises(ValueError, match="by reference"):
            paged.capture_slot(0)


# ---------------------------------------------------------------------------
# preemption end-to-end
# ---------------------------------------------------------------------------


class TestLosslessPreemption:
    @pytest.mark.parametrize("paged", [False, True],
                             ids=["dense", "paged"])
    def test_preempt_resume_stream_identical(self, model, params, paged,
                                             isolated_tokens):
        """A high-priority arrival evicts the lone low-priority DECODE
        stream mid-flight; both finish with token streams bit-identical
        to isolated runs, the victim's result says so
        (``preempted-resumed``, ``preemptions == 1``), and the paged
        path moves zero K/V bytes (no restore program ever compiles)."""
        eng = _mk_engine(model, params, slots=1, paged=paged)
        sched = sv.ContinuousBatchingScheduler(
            eng, max_queue=8, policy=sv.SchedulingPolicy())
        lo = sv.Request("lo", _prompt(1), max_new_tokens=10, priority=0)
        hi = sv.Request("hi", _prompt(2), max_new_tokens=4, priority=5)
        seen = []
        _logging.add_event_sink(seen.append)
        try:
            sched.submit(lo)
            for _ in range(3):
                sched.step()
            assert sched.phase_of("lo").value == "decode"
            sched.submit(hi)
            results = sched.run()
        finally:
            _logging.remove_event_sink(seen.append)
        assert results["hi"].finish_reason == "length"
        assert results["lo"].finish_reason == "preempted-resumed"
        assert results["lo"].preemptions == 1
        assert results["lo"].tokens == isolated_tokens(lo)
        assert results["hi"].tokens == isolated_tokens(hi)
        kinds = [e["event"] for e in seen]
        assert kinds.count("serving_request_preempted") == 1
        assert kinds.count("serving_request_resumed") == 1
        pre = next(e for e in seen
                   if e["event"] == "serving_request_preempted")
        res = next(e for e in seen
                   if e["event"] == "serving_request_resumed")
        assert pre["rid"] == res["rid"] == "lo"
        assert pre["cached_tokens"] == res["cached_tokens"] > 0
        assert sched.control_stats == {"preempted": 1, "resumed": 1,
                                       "cancelled": 0, "shed": 0}
        # no new compiled programs on the policy path
        assert eng.decode_compiles() == 1
        assert eng.prefill_compiles() <= len(eng.prefill_buckets)
        if paged:
            # zero-copy: capture is block references, resume is table
            # aliasing — neither the read nor the restore family exists
            assert eng.capture_compiles() == 0
            assert eng.restore_compiles() == 0
            assert eng.block_pool.cow_total == 0
            # the suspension hold was dropped: pool fully drained
            assert eng.block_pool.used_blocks == 0
        else:
            assert eng.restore_compiles() <= len(eng.prefill_buckets)

    def test_loadgen_drains_suspended_streams(self, eng1):
        """Review regression: the preemptor can finish while the queue
        is empty — the load generator must keep stepping until the
        suspended victim resumes and finishes, not exit with the
        stream orphaned (no result, close() refusing)."""
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=8, policy=sv.SchedulingPolicy(),
            clock=sv.VirtualClock())
        wl = sv.OpenLoopWorkload(
            requests=(sv.Request("lo", _prompt(1), max_new_tokens=12,
                                 priority=0),
                      sv.Request("hi", _prompt(2), max_new_tokens=2,
                                 priority=5)),
            arrivals=(0.0, 1.0), deadlines=(None, None))
        out = sv.LoadGenerator(sched, wl, step_time_s=0.25).run()
        assert sched.control_stats["preempted"] == 1
        assert sched.suspended_count == 0          # fully drained
        assert out.results["lo"].finish_reason == "preempted-resumed"
        assert out.results["hi"].finish_reason == "length"
        assert out.completed == 2

    def test_paged_no_preempt_for_infeasible_admission(self, model,
                                                       params):
        """Review regression: when the pool cannot cover the
        high-priority admission while the victim lives, the victim
        must NOT be evicted — its suspension hold would keep its own
        blocks unavailable and livelock a tight pool.  The admission
        waits instead; the victim finishes, frees its blocks, and the
        high-priority request serves."""
        eng = _mk_engine(model, params, slots=1, paged=True,
                         num_blocks=4)            # 3 allocatable
        sched = sv.ContinuousBatchingScheduler(
            eng, max_queue=8, policy=sv.SchedulingPolicy(),
            clock=sv.VirtualClock())
        # lo worst-case 17 rows = 2 blocks; hi 31 rows = 2 blocks —
        # infeasible while lo is live, trivially feasible after
        sched.submit(sv.Request("lo", _prompt(1), max_new_tokens=10))
        for _ in range(3):
            sched.step()
        sched.submit(sv.Request("hi", _prompt(2), max_new_tokens=24,
                                priority=5))
        results = sched.run()                     # no SchedulerStalled
        assert sched.control_stats["preempted"] == 0
        assert results["lo"].finish_reason == "length"
        assert results["hi"].finish_reason == "length"

    def test_equal_priority_never_preempts(self, eng1):
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=8, policy=sv.SchedulingPolicy())
        sched.submit(sv.Request("a", _prompt(1), max_new_tokens=6,
                                priority=3))
        for _ in range(3):
            sched.step()
        sched.submit(sv.Request("b", _prompt(2), max_new_tokens=3,
                                priority=3))
        results = sched.run()
        assert sched.control_stats["preempted"] == 0
        # FIFO within the class: "a" ran to completion first
        assert results["a"].finish_reason == "length"

    def test_neighbor_stream_untouched_by_preemption(self, engine,
                                                     isolated_tokens):
        """Slot 0's stream decodes straight through while slot 1's
        neighbor is preempted and resumed — bit-identical to its
        isolated run (preemption must not disturb neighbors)."""
        sched = sv.ContinuousBatchingScheduler(
            engine, max_queue=8, policy=sv.SchedulingPolicy())
        keep = sv.Request("keep", _prompt(11), max_new_tokens=12,
                          priority=1)
        lo = sv.Request("lo", _prompt(12), max_new_tokens=12, priority=0)
        hi = sv.Request("hi", _prompt(13), max_new_tokens=3, priority=5)
        sched.submit(keep)
        sched.submit(lo)
        for _ in range(3):
            sched.step()
        sched.submit(hi)           # evicts "lo" (lowest priority)
        results = sched.run()
        assert sched.control_stats["preempted"] == 1
        assert results["lo"].preemptions == 1
        for req in (keep, lo, hi):
            assert (results[req.rid].tokens
                    == isolated_tokens(req)), req.rid
        assert results["keep"].finish_reason == "length"   # never moved


# ---------------------------------------------------------------------------
# cancellation
# ---------------------------------------------------------------------------


class TestCancel:
    def test_cancel_every_phase_and_unknown(self, eng1):
        sched = sv.ContinuousBatchingScheduler(eng1, max_queue=8)
        a = sv.Request("a", _prompt(1), max_new_tokens=8)
        b = sv.Request("b", _prompt(2), max_new_tokens=4)
        sched.submit(a)
        sched.submit(b)
        sched.step()                       # a active, b queued
        assert sched.cancel("b") is True   # queued cancel
        for _ in range(2):
            sched.step()
        assert sched.cancel("a") is True   # decode cancel, slot freed
        assert eng1.free_slots() == [0]
        results = sched.run()
        assert results["a"].finish_reason == "cancelled"
        assert 0 < len(results["a"].tokens) < 8   # partial output kept
        assert results["b"].finish_reason == "cancelled"
        assert results["b"].tokens == []
        assert np.isnan(results["b"].ttft_s)      # no first token
        assert sched.cancel("a") is False         # already terminal
        with pytest.raises(KeyError, match="unknown rid"):
            sched.cancel("never-submitted")
        assert sched.control_stats["cancelled"] == 2

    def test_cancel_suspended_releases_paged_hold(self, model, params):
        eng = _mk_engine(model, params, slots=1, paged=True)
        sched = sv.ContinuousBatchingScheduler(
            eng, max_queue=8, policy=sv.SchedulingPolicy())
        sched.submit(sv.Request("lo", _prompt(1), max_new_tokens=10))
        for _ in range(3):
            sched.step()
        sched.submit(sv.Request("hi", _prompt(2), max_new_tokens=4,
                                priority=5))
        sched.step()                       # preempts "lo"
        assert sched.suspended_count == 1
        held = eng.block_pool.used_blocks
        assert held > 0
        assert sched.cancel("lo") is True
        results = sched.run()
        assert results["lo"].finish_reason == "cancelled"
        assert results["hi"].finish_reason == "length"
        assert eng.block_pool.used_blocks == 0    # hold released

    def test_cancel_mid_prefill_releases_prefix_pins(self, eng1):
        """Pin-leak regression: a cancelled mid-PREFILL stream was
        pinning the chain it extended — cancel must release every pin
        or those entries can never be evicted."""
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=4,
            prefill_budget=16,
            prefix_caching=sv.PrefixCacheConfig(max_tokens=64))
        long_prompt = _prompt(3, 48)       # 3 budget-16 steps to cache
        sched.submit(sv.Request("long", long_prompt, max_new_tokens=2))
        sched.step()                       # one chunk cached + offered
        assert sched.phase_of("long").value == "prefill"
        pc = sched.prefix_cache
        assert [e for e in pc._entries.values() if e.refs], \
            "test premise: the mid-prefill stream holds pins"
        assert sched.cancel("long") is True
        assert not [e for e in pc._entries.values() if e.refs], \
            "cancel leaked prefix-cache pins"
        sched.run()
        sched.close()

    def test_cancel_neighbor_isolation(self, engine, isolated_tokens):
        sched = sv.ContinuousBatchingScheduler(engine, max_queue=8)
        keep = sv.Request("keep", _prompt(21), max_new_tokens=8)
        gone = sv.Request("gone", _prompt(22), max_new_tokens=8)
        sched.submit(keep)
        sched.submit(gone)
        for _ in range(3):
            sched.step()
        sched.cancel("gone")
        results = sched.run()
        assert (results["keep"].tokens
                == isolated_tokens(keep))


# ---------------------------------------------------------------------------
# deadline shedding
# ---------------------------------------------------------------------------


class TestDeadlineShedding:
    def test_shed_at_admission_and_mid_queue(self, eng1):
        """Both shapes: a request whose deadline passed before it was
        ever considered (admission-time) and one that expires while
        waiting behind a long stream (mid-queue) are shed without
        spending prefill budget; a deadline-free neighbor is not."""
        clk = sv.VirtualClock()
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=8, policy=sv.SchedulingPolicy(), clock=clk)
        seen = []
        _logging.add_event_sink(seen.append)
        try:
            sched.submit(sv.Request("slow", _prompt(1),
                                    max_new_tokens=12))
            sched.step()                   # "slow" owns the only slot
            sched.submit(sv.Request("due", _prompt(2), max_new_tokens=2,
                                    deadline_s=1.0))
            sched.submit(sv.Request("ok", _prompt(3), max_new_tokens=2))
            clk.advance(0.5)
            sched.step()                   # deadline not yet passed
            assert sched.phase_of("due").value == "queued"
            clk.advance(1.0)               # now 1.5s > 1.0s deadline
            results = sched.run()
        finally:
            _logging.remove_event_sink(seen.append)
        assert results["due"].finish_reason == "shed"
        assert results["due"].tokens == []
        assert results["ok"].finish_reason == "length"
        assert results["slow"].finish_reason == "length"
        shed_events = [e for e in seen
                       if e["event"] == "serving_request_shed"]
        assert len(shed_events) == 1
        assert shed_events[0]["rid"] == "due"
        assert shed_events[0]["waited_s"] >= 1.0
        # the shed prompt never reached a prefill chunk
        assert not any(e["event"] == "serving_prefill_chunk"
                       and e["rid"] == "due" for e in seen)
        assert sched.control_stats["shed"] == 1

    def test_loadgen_charges_policy_sheds_to_goodput(self, eng1):
        """A policy-shed request has a result, but goodput counts it
        as a miss — finishing early by giving up is not service."""
        clk = sv.VirtualClock()
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=8, policy=sv.SchedulingPolicy(), clock=clk)
        prompts = [_prompt(i) for i in range(4)]
        wl = sv.make_workload(prompts, sv.uniform_arrivals(4, 100.0),
                              max_new_tokens=8, deadline_s=2.0)
        out = sv.LoadGenerator(sched, wl, step_time_s=0.5).run()
        reasons = {r.rid: r.finish_reason for r in out.results.values()}
        assert "shed" in set(reasons.values())
        served = [rid for rid, why in reasons.items()
                  if why in sv.SERVED_REASONS]
        assert out.completed == len(served) < 4
        for rid, why in reasons.items():
            if why == "shed":
                assert out.met_deadline[rid] is False
        assert out.goodput < 1.0

    def test_shedding_off_keeps_expired_requests(self, eng1):
        clk = sv.VirtualClock()
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=8, clock=clk,
            policy=sv.SchedulingPolicy(deadline_shedding=False))
        sched.submit(sv.Request("x", _prompt(1), max_new_tokens=2,
                                deadline_s=0.5))
        clk.advance(2.0)
        results = sched.run()
        assert results["x"].finish_reason == "length"   # served late
        assert sched.control_stats["shed"] == 0


# ---------------------------------------------------------------------------
# tenant fairness
# ---------------------------------------------------------------------------


class TestTenantFairness:
    def test_inflight_cap_blocks_a_flood(self, engine):
        """Tenant A floods the queue first; with a cap of 1, A never
        holds both slots and B's later arrivals are served alongside —
        admission order interleaves instead of draining A first."""
        sched = sv.ContinuousBatchingScheduler(
            engine, max_queue=16,
            policy=sv.SchedulingPolicy(max_inflight_per_tenant=1))
        for i in range(3):
            sched.submit(sv.Request(f"a{i}", _prompt(i),
                                    max_new_tokens=4, tenant="A"))
        for i in range(2):
            sched.submit(sv.Request(f"b{i}", _prompt(10 + i),
                                    max_new_tokens=4, tenant="B"))
        admitted = []
        seen = []
        _logging.add_event_sink(seen.append)
        try:
            while sched.queue_depth or sched.active_count:
                sched.step()
                counts = {}
                for rid in sched.active_rids:
                    tenant = rid[0].upper()
                    counts[tenant] = counts.get(tenant, 0) + 1
                assert counts.get("A", 0) <= 1     # the cap held
                assert counts.get("B", 0) <= 1
        finally:
            _logging.remove_event_sink(seen.append)
        admitted = [e["rid"] for e in seen
                    if e["event"] == "serving_request_admitted"]
        # B was admitted while A still had queued requests
        assert admitted.index("b0") < admitted.index("a2")

    def test_wrr_interleaves_admissions_by_weight(self, eng1):
        """slots=1, everything queued up front: admission order IS the
        WRR order — weight 2:1 serves A twice per B, interleaved."""
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=16,
            policy=sv.SchedulingPolicy(tenant_weights={"A": 2.0,
                                                       "B": 1.0}))
        for i in range(4):
            sched.submit(sv.Request(f"a{i}", _prompt(i),
                                    max_new_tokens=2, tenant="A"))
        for i in range(2):
            sched.submit(sv.Request(f"b{i}", _prompt(10 + i),
                                    max_new_tokens=2, tenant="B"))
        seen = []
        _logging.add_event_sink(seen.append)
        try:
            sched.run()
        finally:
            _logging.remove_event_sink(seen.append)
        admitted = [e["rid"] for e in seen
                    if e["event"] == "serving_request_admitted"]
        assert admitted == ["a0", "b0", "a1", "a2", "b1", "a3"]

    def test_tenant_inflight_gauge(self, engine):
        from apex_tpu.obs.bridge import SERVING_TENANT_INFLIGHT

        sched = sv.ContinuousBatchingScheduler(
            engine, max_queue=8, policy=sv.SchedulingPolicy())
        sched.submit(sv.Request("a0", _prompt(1), max_new_tokens=6,
                                tenant="A"))
        sched.submit(sv.Request("b0", _prompt(2), max_new_tokens=6,
                                tenant="B"))
        sched.step()
        assert SERVING_TENANT_INFLIGHT.value(tenant="A") == 1
        assert SERVING_TENANT_INFLIGHT.value(tenant="B") == 1
        sched.run()
        assert SERVING_TENANT_INFLIGHT.value(tenant="A") == 0
        assert SERVING_TENANT_INFLIGHT.value(tenant="B") == 0


# ---------------------------------------------------------------------------
# satellites: O(1) submit guard, run() stall bound, close() lifecycle
# ---------------------------------------------------------------------------


class TestSatellites:
    def test_duplicate_rid_semantics_preserved(self, eng1):
        sched = sv.ContinuousBatchingScheduler(eng1, max_queue=8)
        sched.submit(sv.Request("r", _prompt(1), max_new_tokens=2))
        with pytest.raises(ValueError, match="in flight"):
            sched.submit(sv.Request("r", _prompt(2), max_new_tokens=2))
        sched.run()
        with pytest.raises(ValueError, match="finished"):
            sched.submit(sv.Request("r", _prompt(2), max_new_tokens=2))
        sched.pop_result("r")              # claiming frees the rid
        sched.submit(sv.Request("r", _prompt(2), max_new_tokens=2))
        sched.run()
        assert set(sched.pop_results()) == {"r"}
        sched.submit(sv.Request("r", _prompt(3), max_new_tokens=2))
        sched.run()

    def test_run_raises_scheduler_stalled(self, eng1):
        sched = sv.ContinuousBatchingScheduler(eng1, max_queue=8)
        sched.submit(sv.Request("r", _prompt(1), max_new_tokens=2))
        # an engine bug that never finishes a stream: a no-op step
        sched.step = lambda: []
        with pytest.raises(sv.SchedulerStalled) as exc:
            sched.run()
        msg = str(exc.value)
        assert "1 queued" in msg and "prefill backlog" in msg
        # explicit max_steps is a progress bound too
        with pytest.raises(sv.SchedulerStalled):
            sched.run(max_steps=3)

    def test_derived_bound_is_generous_for_healthy_drains(self, eng1):
        sched = sv.ContinuousBatchingScheduler(eng1, max_queue=8)
        for i in range(3):
            sched.submit(sv.Request(f"r{i}", _prompt(i),
                                    max_new_tokens=4))
        bound = sched._derived_step_bound()
        results = sched.run()
        assert len(results) == 3
        assert sched.steps_run < bound / 2     # nowhere near the bound

    def test_close_twice_and_close_with_work(self, eng1):
        sched = sv.ContinuousBatchingScheduler(
            eng1, max_queue=4,
            prefix_caching=sv.PrefixCacheConfig(max_tokens=64))
        sched.submit(sv.Request("r", _prompt(1), max_new_tokens=8))
        with pytest.raises(RuntimeError, match="queued"):
            sched.close()                  # queued work refuses
        sched.step()
        with pytest.raises(RuntimeError, match="active"):
            sched.close()                  # active work refuses
        sched.run()
        sched.close()
        sched.close()                      # idempotent once drained
        # suspended work refuses too
        sched2 = sv.ContinuousBatchingScheduler(
            eng1, max_queue=8, policy=sv.SchedulingPolicy(),
            prefix_caching=sv.PrefixCacheConfig(max_tokens=64))
        sched2.submit(sv.Request("lo", _prompt(1), max_new_tokens=10))
        for _ in range(3):
            sched2.step()
        sched2.submit(sv.Request("hi", _prompt(2), max_new_tokens=4,
                                 priority=5))
        sched2.step()
        assert sched2.suspended_count == 1
        with pytest.raises(RuntimeError, match="suspended"):
            sched2.close()
        sched2.run()
        sched2.close()


# ---------------------------------------------------------------------------
# chaos fault units
# ---------------------------------------------------------------------------


class TestChaosFaults:
    def test_slow_decode_step_inflates_virtual_clock(self):
        clk = sv.VirtualClock()
        fault = SlowDecodeStep([1, 3], 0.5, clock=clk)
        for step in range(5):
            fault(step)
        assert clk() == 1.0                # exactly two inflations
        with pytest.raises(ValueError, match="extra_s"):
            SlowDecodeStep([0], 0.0, clock=clk)
        with pytest.raises(ValueError, match="advanceable"):
            SlowDecodeStep([0], 0.5, clock=lambda: 0.0)

    def test_stall_stream_cancels_after_n_tokens(self, engine,
                                                 isolated_tokens):
        sched = sv.ContinuousBatchingScheduler(engine, max_queue=8,
                                               clock=sv.VirtualClock())
        keep = sv.Request("keep", _prompt(1), max_new_tokens=8)
        wl = sv.OpenLoopWorkload(
            requests=(keep,
                      sv.Request("stall", _prompt(2), max_new_tokens=8)),
            arrivals=(0.0, 0.0), deadlines=(None, None))
        fault = StallStream(["stall"], after_tokens=3)
        out = sv.LoadGenerator(sched, wl, step_time_s=0.25,
                               step_hook=fault).run()
        assert fault.stalled == ["stall"]
        res = out.results["stall"]
        assert res.finish_reason == "cancelled"
        assert 3 <= len(res.tokens) < 8
        assert (out.results["keep"].tokens
                == isolated_tokens(keep))

    def test_cancel_storm_deterministic_and_isolated(self, engine,
                                                     isolated_tokens):
        def run_storm():
            engine.reset()
            sched = sv.ContinuousBatchingScheduler(
                engine, max_queue=16, clock=sv.VirtualClock())
            prompts = [_prompt(i) for i in range(6)]
            wl = sv.make_workload(prompts, (0.0,) * 6,
                                  max_new_tokens=6, rid_prefix="s")
            storm = CancelStorm([2], count=2, seed=3)
            out = sv.LoadGenerator(sched, wl, step_time_s=0.25,
                                   step_hook=storm).run()
            return storm.cancelled, out

        hit1, out1 = run_storm()
        hit2, out2 = run_storm()
        assert hit1 == hit2 and len(hit1) == 2     # seed-deterministic
        for req in out1.results:
            assert out1.results[req].tokens == out2.results[req].tokens
        survivors = [r for r in out1.results.values()
                     if r.finish_reason in sv.SERVED_REASONS]
        assert survivors
        wl_by_rid = {f"s{i}": i for i in range(6)}
        for res in survivors:
            ref = isolated_tokens(
                sv.Request(res.rid, _prompt(wl_by_rid[res.rid]),
                           max_new_tokens=6))
            assert res.tokens == ref


# ---------------------------------------------------------------------------
# default-off identity: no policy == the FIFO scheduler, byte for byte
# ---------------------------------------------------------------------------


def _serving_metric_state():
    snap = obs.snapshot()
    return {name: entry for name, entry in snap.items()
            if name.startswith("apex_serving_")
            or name == "apex_events_total"}


class TestDefaultOffIdentity:
    def test_policy_annotations_inert_without_policy(self, engine):
        """The SAME workload, once with control-plane annotations
        (priorities, deadlines, tenants) and once with plain requests,
        through policy-less schedulers: event streams (kind, rid,
        sorted payload keys) and serving-metric snapshots are EXACTLY
        equal — the annotations are inert, and the refactored
        admission path is byte-for-byte the FIFO scheduler."""
        def one_run(annotated):
            clk = sv.VirtualClock()
            engine.reset()
            sched = sv.ContinuousBatchingScheduler(engine, max_queue=8,
                                                   clock=clk)
            prompts = [_prompt(i) for i in range(5)]
            wl = sv.make_workload(
                prompts, sv.burst_arrivals(5, burst=2, period_s=1.0),
                max_new_tokens=3,
                deadline_s=0.75 if annotated else None,
                priorities=[5, 0] if annotated else None,
                tenants=["paid", "free"] if annotated else None)
            seen = []
            _logging.add_event_sink(seen.append)
            obs.metrics.reset()
            try:
                out = sv.LoadGenerator(sched, wl,
                                       step_time_s=0.25).run()
            finally:
                _logging.remove_event_sink(seen.append)
            stream = [(e["event"], e.get("rid"), tuple(sorted(e)))
                      for e in seen]
            tokens = {rid: r.tokens for rid, r in out.results.items()}
            return stream, _serving_metric_state(), tokens

        s_plain, m_plain, t_plain = one_run(annotated=False)
        s_annot, m_annot, t_annot = one_run(annotated=True)
        assert s_annot == s_plain
        assert t_annot == t_plain
        # the deadline-carrying run publishes goodput (a loadgen
        # feature that predates this PR) — everything else identical
        m_annot.pop("apex_serving_goodput_ratio", None)
        m_plain.pop("apex_serving_goodput_ratio", None)
        assert m_annot == m_plain
        # and no control-plane event kind ever fired
        control = {"serving_request_preempted", "serving_request_resumed",
                   "serving_request_cancelled", "serving_request_shed"}
        assert not control & {k for k, _, _ in s_annot}


# ---------------------------------------------------------------------------
# THE acceptance run: 2x-overload chaos, policy vs FIFO
# ---------------------------------------------------------------------------


class TestChaosAcceptance:
    N = 10
    #: burst 1 (cx0..cx4) is all low priority; burst 2 carries the
    #: high-priority arrivals (cx5, cx7) — they land while both slots
    #: hold low-priority DECODE streams, forcing preempt-to-admit
    PRIORITIES = (0, 0, 0, 0, 0, 5, 0, 5, 0, 0)
    TENANTS = ("batch",) * 5 + ("paid", "batch", "paid", "batch",
                                "batch")
    HI = (5, 7)

    def _workload(self):
        prompts = [_prompt(100 + i) for i in range(self.N)]
        return sv.make_workload(
            prompts, sv.burst_arrivals(self.N, burst=5, period_s=2.0),
            max_new_tokens=6, deadline_s=4.0,
            priorities=self.PRIORITIES, tenants=self.TENANTS,
            rid_prefix="cx")

    def _drive(self, model, params, policy):
        clk = sv.VirtualClock()
        eng = _mk_engine(model, params, slots=2)
        sched = sv.ContinuousBatchingScheduler(
            eng, max_queue=16, policy=policy, clock=clk)
        rec = rt.RequestTraceRecorder(clock=clk).install()
        chaos = SlowDecodeStep([3, 9], 1.0, clock=clk)
        try:
            out = sv.LoadGenerator(sched, self._workload(),
                                   step_time_s=0.25,
                                   step_hook=chaos).run()
        finally:
            rec.uninstall()
        return sched, eng, out, rec

    @pytest.fixture(scope="class")
    def runs(self, model, params):
        fifo = self._drive(model, params, policy=None)
        pol = self._drive(model, params,
                          policy=sv.SchedulingPolicy(
                              tenant_weights={"paid": 3.0}))
        return fifo, pol

    def test_chaos_exercised_the_control_plane(self, runs):
        (fifo_sched, _, _, _), (sched, _, out, _) = runs
        stats = sched.control_stats
        assert stats["preempted"] >= 2, stats
        assert stats["resumed"] == stats["preempted"]   # all came back
        assert stats["shed"] >= 1, stats
        # the FIFO side of the comparison ran no control plane at all
        assert fifo_sched.control_stats == {
            "preempted": 0, "resumed": 0, "cancelled": 0, "shed": 0}

    def test_survivors_bit_identical_to_unperturbed_runs(
            self, runs, isolated_tokens):
        """Every stream that survived the chaos run — including every
        preempted-and-resumed one — is token-identical to its
        unperturbed isolated run: neither the slow steps, nor the
        shedding around it, nor a lossless preemption moved one bit."""
        (_, _, fifo_out, _), (_, _, pol_out, _) = runs
        wl = self._workload()
        by_rid = {r.rid: r for r in wl.requests}
        checked = resumed = 0
        for out in (fifo_out, pol_out):
            for rid, res in out.results.items():
                if res.finish_reason not in sv.SERVED_REASONS:
                    continue
                assert res.tokens == isolated_tokens(by_rid[rid]), rid
                checked += 1
                resumed += res.finish_reason == "preempted-resumed"
        assert checked >= self.N            # FIFO serves all 10
        assert resumed >= 1                 # incl. a preempted stream

    def test_policy_beats_fifo_on_hp_p99_ttft_and_goodput(self, runs):
        """The headline: on the same 2x-overload chaos workload, the
        policy's high-priority p99 TTFT and overall goodput are
        STRICTLY better than FIFO's (the PR-12 SLO-report semantics:
        goodput over offered, deadlines from arrival)."""
        (_, _, fifo_out, fifo_rec), (_, _, pol_out, pol_rec) = runs
        hi_rids = {f"cx{i}" for i in self.HI}

        def report(out, rec):
            return oslo.build_report(
                rec.records(), offered=out.offered,
                deadlines=out.deadlines, arrivals=out.arrivals,
                duration_s=out.duration_s)

        def hp_p99(rec):
            samples = [r.ttft_s for r in rec.records()
                       if r.rid in hi_rids and r.complete]
            assert len(samples) == len(hi_rids)   # every hp served
            return oslo.percentile(samples, 0.99)

        fifo_report = report(fifo_out, fifo_rec)
        pol_report = report(pol_out, pol_rec)
        assert hp_p99(pol_rec) < hp_p99(fifo_rec)
        assert pol_report.goodput > fifo_report.goodput
        assert pol_out.goodput > fifo_out.goodput
        # recorder-side annotations agree with the scheduler
        pre = [r for r in pol_rec.records() if r.preemptions]
        assert pre and all(p["t_resumed"] is not None
                           for r in pre for p in r.preempts
                           if r.finish_reason == "preempted-resumed")

    def test_no_new_compiled_programs_on_the_policy_path(self, runs):
        (_, fifo_eng, _, _), (_, pol_eng, _, _) = runs
        for eng in (fifo_eng, pol_eng):
            assert eng.decode_compiles() == 1
            assert eng.prefill_compiles() <= len(eng.prefill_buckets)
        # preempt/resume reuses the existing read/restore families
        bound = len(pol_eng.prefill_buckets) + \
            pol_eng.prefill_buckets[0] - 1
        assert pol_eng.capture_compiles() <= bound
        assert pol_eng.restore_compiles() <= len(pol_eng.prefill_buckets)
        # FIFO never paid either family
        assert fifo_eng.capture_compiles() == 0
        assert fifo_eng.restore_compiles() == 0
