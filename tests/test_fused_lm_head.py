"""Fused LM-head cross-entropy kernel parity (ops/fused_lm_head.py).

The kernel must match the materialized reference (and the tp-world-1
vocab_parallel_cross_entropy path it replaces in GPTModel) for values and
gradients, including a non-tile-aligned vocab exercising the padded tail.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.fused_lm_head import (
    fused_lm_head_loss,
    lm_head_loss_reference,
)


@pytest.fixture(autouse=True)
def _interpret_kernels(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "interpret")
    yield


@pytest.mark.parametrize("vocab", [1000, 768])  # padded + aligned tails
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_matches_reference(rng, vocab, dtype):
    T, H = 256, 128
    h = jnp.asarray(rng.standard_normal((T, H)) * 0.5, dtype)
    e = jnp.asarray(rng.standard_normal((vocab, H)) * 0.5, dtype)
    lab = jnp.asarray(rng.integers(0, vocab, (T,)), jnp.int32)

    out = fused_lm_head_loss(h, e, lab, block_t=128, block_v=384)
    ref = lm_head_loss_reference(h, e, lab)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=tol, atol=tol)

    def f_fused(h, e):
        return fused_lm_head_loss(h, e, lab, block_t=128, block_v=384).mean()

    def f_ref(h, e):
        return lm_head_loss_reference(h, e, lab).mean()

    gf = jax.grad(f_fused, argnums=(0, 1))(h, e)
    gr = jax.grad(f_ref, argnums=(0, 1))(h, e)
    gtol = 1e-4 if dtype == jnp.float32 else 4e-2
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=gtol, atol=gtol)


def test_leading_shape_and_fallback(rng):
    # [b, s] leading shape; T=6 not divisible by block_t -> jnp fallback
    b, s, H, V = 2, 3, 128, 512
    h = jnp.asarray(rng.standard_normal((b, s, H)), jnp.float32)
    e = jnp.asarray(rng.standard_normal((V, H)), jnp.float32)
    lab = jnp.asarray(rng.integers(0, V, (b, s)), jnp.int32)
    out = fused_lm_head_loss(h, e, lab)
    assert out.shape == (b, s)
    ref = lm_head_loss_reference(h.reshape(-1, H), e, lab.reshape(-1))
    np.testing.assert_allclose(np.asarray(out).ravel(), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_out_of_range_labels_are_path_independent(rng):
    """Labels outside [0, vocab) (e.g. ignore_index -100) are unsupported
    but must be DETERMINISTIC and path-independent (r3 advisor finding):
    kernel and materialized fallback both return lse (target logit 0), so
    shape-driven routing cannot flip the value silently."""
    T, H, V = 256, 128, 512
    h = jnp.asarray(rng.standard_normal((T, H)) * 0.5, jnp.float32)
    e = jnp.asarray(rng.standard_normal((V, H)) * 0.5, jnp.float32)
    lab = np.asarray(rng.integers(0, V, (T,)), np.int32)
    lab[::7] = -100          # torch-style ignore_index
    lab[3::11] = V + 5       # past the (padded) vocab
    lab = jnp.asarray(lab)

    kernel = fused_lm_head_loss(h, e, lab, block_t=128, block_v=384)
    ref = lm_head_loss_reference(h, e, lab)
    np.testing.assert_allclose(np.asarray(kernel), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # out-of-range rows are exactly lse (target contribution 0) — bigger
    # than any real CE row's target term would allow on average
    logits = np.asarray(h, np.float64) @ np.asarray(e, np.float64).T
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) \
        + logits.max(-1)
    np.testing.assert_allclose(np.asarray(kernel)[::7], lse[::7], rtol=1e-4)


def test_gpt_model_routes_through_fused_head(rng):
    """GPTModel(tp world 1) training loss must equal the materialized
    vocab-parallel CE it replaces, through the whole model."""
    from apex_tpu.transformer.testing import GPTModel

    vocab = 512
    model = GPTModel(num_layers=2, hidden_size=128, num_attention_heads=4,
                     vocab_size=vocab, max_sequence_length=64)
    ids = jnp.asarray(rng.integers(0, vocab, (2, 64)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), ids)

    loss = model.apply(params, ids, labels=labels)
    assert loss.shape == (2, 64)
    # reference: logits path through the same params
    logits = model.apply(params, ids)  # [s, b, v]
    logits = jnp.asarray(logits).transpose(1, 0, 2)  # [b, s, v]
    m = logits.max(axis=-1)
    lse = m + jnp.log(jnp.exp(logits - m[..., None]).sum(axis=-1))
    tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(lse - tgt),
                               rtol=1e-4, atol=1e-4)
