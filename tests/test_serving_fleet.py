"""Fault-tolerant fleet serving: replica router with health checks,
lossless stream failover, and fleet-scale chaos.

THE acceptance run: a 3-replica fleet under a 2x open-loop overload,
``KillReplica`` hard-killing a replica mid-stream — every victim
resumes on a survivor and its final token stream is bit-identical to
an unperturbed isolated run; zero admitted streams are dropped; the
failover fleet's goodput strictly beats a no-failover fleet on the
same workload with the same chaos schedule.  The tp=2 variant pins the
same contract token-identically (psum drift is argmax-tier).

A router of one replica is the identity: the LoadGenerator result over
``FleetRouter({"r0": sched})`` equals the result over ``sched``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging, obs
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.resilience.fault_injection import (
    KillReplica,
    SlowReplica,
    WedgeReplica,
)
from apex_tpu.serving.engine import TPConfig

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


@pytest.fixture(scope="module")
def _fleet_mod(model, params):
    """Three independent 2-slot dense engines — the fleet.  Module
    -scoped: every jit family compiles once per engine (~seconds each
    on CPU), so tests share them and reset between."""
    return tuple(sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                                 prefill_len=32) for _ in range(3))


@pytest.fixture
def fleet_engines(_fleet_mod):
    for e in _fleet_mod:
        e.reset()
    return _fleet_mod


@pytest.fixture(scope="module")
def _ref_mod(model, params):
    return sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=32)


@pytest.fixture(scope="module")
def isolated_tokens(_ref_mod):
    """``fn(request) -> tokens``: the request's stream run alone on a
    FIFO scheduler — the unperturbed reference every failover survivor
    must match bit for bit."""
    eng = _ref_mod
    memo = {}

    def run(request):
        key = (tuple(request.prompt), request.max_new_tokens,
               request.eos_id, request.temperature, request.top_k,
               request.seed)
        if key not in memo:
            eng.reset()
            sched = sv.ContinuousBatchingScheduler(eng, max_queue=4)
            sched.submit(sv.Request("ref", request.prompt,
                                    max_new_tokens=request.max_new_tokens,
                                    eos_id=request.eos_id,
                                    temperature=request.temperature,
                                    top_k=request.top_k,
                                    seed=request.seed))
            memo[key] = sched.run()["ref"].tokens
        return memo[key]

    return run


def _prompt(seed, n=8):
    return [int(x)
            for x in np.random.default_rng(seed).integers(0, 128, n)]


def _mk_fleet(engines, clk, *, max_queue=8, prefix=False, config=None):
    scheds = {
        f"r{i}": sv.ContinuousBatchingScheduler(
            e, max_queue=max_queue, log_interval=10 ** 9, clock=clk,
            prefix_caching=(sv.PrefixCacheConfig() if prefix else None))
        for i, e in enumerate(engines)}
    return sv.FleetRouter(scheds,
                          config=config if config is not None
                          else sv.FleetConfig())


class _EventTap:
    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


# ---------------------------------------------------------------------------
# router units: construction, identity, placement
# ---------------------------------------------------------------------------


class TestFleetUnits:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="suspect_after_s"):
            sv.FleetConfig(suspect_after_s=0.0)
        with pytest.raises(ValueError, match="must exceed"):
            sv.FleetConfig(suspect_after_s=2.0, dead_after_s=1.0)

    def test_router_validation(self, fleet_engines):
        e0, e1, _ = fleet_engines
        clk = sv.VirtualClock()
        with pytest.raises(ValueError, match="at least one"):
            sv.FleetRouter({})
        s0 = sv.ContinuousBatchingScheduler(e0, clock=clk)
        s_other_clock = sv.ContinuousBatchingScheduler(
            e1, clock=sv.VirtualClock())
        with pytest.raises(ValueError, match="share the fleet clock"):
            sv.FleetRouter({"a": s0, "b": s_other_clock})
        s_same_engine = sv.ContinuousBatchingScheduler(e0, clock=clk)
        with pytest.raises(ValueError, match="shares an engine"):
            sv.FleetRouter({"a": s0, "b": s_same_engine})
        with pytest.raises(ValueError, match="unknown replicas"):
            sv.FleetRouter(
                {"a": s0},
                config=sv.FleetConfig(weights={"zz": 2.0}))

    def test_router_of_one_is_identity(self, fleet_engines):
        """Satellite: the LoadGenerator drives any submit/step/results
        target — a fleet of one replica reproduces the bare
        scheduler's run exactly (same tokens, same completions, same
        goodput, same step count), and the workload fingerprint the
        bench keys on is untouched by the wrapping."""
        e0 = fleet_engines[0]
        prompts = [_prompt(i) for i in range(5)]
        wl = sv.make_workload(prompts, sv.uniform_arrivals(5, 4.0),
                              max_new_tokens=4, deadline_s=30.0)
        fp = wl.schedule_fingerprint()

        def one_run(wrap):
            e0.reset()
            clk = sv.VirtualClock()
            sched = sv.ContinuousBatchingScheduler(
                e0, max_queue=8, log_interval=10 ** 9, clock=clk)
            target = sv.FleetRouter({"r0": sched}) if wrap else sched
            return sv.LoadGenerator(target, wl, step_time_s=0.25).run()

        bare = one_run(wrap=False)
        fleet = one_run(wrap=True)
        assert wl.schedule_fingerprint() == fp
        assert {r: v.tokens for r, v in fleet.results.items()} \
            == {r: v.tokens for r, v in bare.results.items()}
        assert {r: v.finish_reason for r, v in fleet.results.items()} \
            == {r: v.finish_reason for r, v in bare.results.items()}
        assert fleet.rejected == bare.rejected
        assert fleet.completed == bare.completed
        assert fleet.goodput == bare.goodput
        assert fleet.steps == bare.steps

    def test_prefix_affinity_placement_probes_read_only(
            self, fleet_engines, isolated_tokens):
        """A shared-prefix request routes to the replica whose cache
        covers its prompt, and the placement probe never pollutes any
        replica's hit/miss accounting (READ-ONLY probe, not a
        lookup)."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk, prefix=True)
        shared = _prompt(7, n=40)
        warm = sv.Request("warm", shared, max_new_tokens=2)
        router.submit(warm)
        home = router.placement_of("warm")
        assert home is not None
        router.run()
        assert router.replica(home).prefix_cache.stats()["entries"] > 0
        stats_before = {n: router.replica(n).prefix_cache.stats()
                        for n in router.replica_names}
        hit = sv.Request("hit", shared + [3, 5], max_new_tokens=2)
        router.submit(hit)
        # affinity won over WRR: the request landed on the warm replica
        assert router.placement_of("hit") == home
        # ...and choosing it read no cache: stats byte-identical
        assert {n: router.replica(n).prefix_cache.stats()
                for n in router.replica_names} == stats_before
        out = router.run()
        assert out["hit"].tokens == isolated_tokens(hit)

    def test_wrr_weights_spread_placements(self, fleet_engines):
        """With no cache coverage anywhere, smooth WRR places by
        weight: 2:1:1 over 8 submissions lands 4/2/2."""
        clk = sv.VirtualClock()
        router = _mk_fleet(
            fleet_engines, clk,
            config=sv.FleetConfig(
                weights={"r0": 2.0, "r1": 1.0, "r2": 1.0}))
        for i in range(8):
            router.submit(sv.Request(f"w{i}", _prompt(20 + i),
                                     max_new_tokens=1))
        counts = {"r0": 0, "r1": 0, "r2": 0}
        for i in range(8):
            counts[router.placement_of(f"w{i}")] += 1
        assert counts == {"r0": 4, "r1": 2, "r2": 2}

    def test_queue_full_retries_next_best_then_sheds(self, fleet_engines):
        """A replica's QueueFull moves the submission to the next-best
        candidate; when every healthy replica refuses, the router
        sheds with a fleet event and re-raises QueueFull for the
        open-loop loadgen."""
        clk = sv.VirtualClock()
        # weight r0 so heavily every submission tries it first — its
        # 1-deep queue forces the deterministic retry path
        router = _mk_fleet(
            fleet_engines, clk, max_queue=1,
            config=sv.FleetConfig(weights={"r0": 100.0}))
        with _EventTap() as tap:
            for i in range(3):
                router.submit(sv.Request(f"q{i}", _prompt(30 + i),
                                         max_new_tokens=1))
            with pytest.raises(sv.QueueFull, match="every healthy"):
                router.submit(sv.Request("q3", _prompt(33),
                                         max_new_tokens=1))
        routed = tap.of("serving_fleet_routed")
        assert [e["rid"] for e in routed] == ["q0", "q1", "q2"]
        assert routed[0]["retries"] == 0         # r0 had room
        assert routed[1]["retries"] >= 1         # r0 full: moved on
        shed = tap.of("serving_fleet_shed")
        assert [e["rid"] for e in shed] == ["q3"]
        assert shed[0]["reason"] == "all_full"
        assert router.fleet_stats["shed"] == 1

    def test_replica_reports_partition_by_final_placement(
            self, fleet_engines):
        """Per-replica SLO reports split the request-trace records by
        who FINISHED each stream; the fleet entry aggregates them."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        wl = sv.make_workload(
            [_prompt(70 + i) for i in range(6)],
            sv.uniform_arrivals(6, 12.0), max_new_tokens=3,
            deadline_s=30.0, rid_prefix="rr")
        with obs.recording_requests(clock=clk) as rec:
            out = sv.LoadGenerator(router, wl,
                                   step_time_s=0.25).run()
        assert out.completed == 6
        reports = router.replica_reports(
            rec.records(), deadlines=out.deadlines,
            arrivals=out.arrivals, duration_s=out.duration_s)
        assert "fleet" in reports
        fleet = reports["fleet"]
        assert fleet.completed == 6 and fleet.goodput == 1.0
        per_replica = {k: v for k, v in reports.items() if k != "fleet"}
        assert sum(r.completed for r in per_replica.values()) == 6
        for name, rep in per_replica.items():
            served = [rid for rid in out.results
                      if router.placement_of(rid) == name]
            assert rep.completed == len(served) > 0


# ---------------------------------------------------------------------------
# health state machine + failover fidelities
# ---------------------------------------------------------------------------


class TestFleetHealth:
    def test_straggler_goes_suspect_then_recovers(self, fleet_engines):
        """SlowReplica: missed beats past suspect_after_s drive
        HEALTHY→SUSPECT (no new placements), and the next completed
        beat recovers HEALTHY with WRR credits reset."""
        clk = sv.VirtualClock()
        # the straggler's clock inflation ages EVERY replica's last
        # beat (one shared timeline), so the suspect threshold sits
        # between the healthy inter-beat gap (0.5s on stalled steps)
        # and the straggler's two-missed-beats age (1.0s)
        router = _mk_fleet(
            fleet_engines, clk,
            config=sv.FleetConfig(suspect_after_s=0.75,
                                  dead_after_s=5.0))
        fault = SlowReplica("r1", steps=[0, 1], extra_s=0.25, clock=clk)
        with _EventTap() as tap:
            for step in range(4):
                router.step()
                fault(step, router)
                clk.advance(0.25)
                if router.state_of("r1") is sv.ReplicaState.SUSPECT:
                    # a suspect replica takes no new placements
                    router.submit(sv.Request(f"s{step}", _prompt(40),
                                             max_new_tokens=1))
                    assert router.placement_of(f"s{step}") != "r1"
        trans = [(e["replica"], e["state"])
                 for e in tap.of("serving_fleet_replica_state")]
        assert trans == [("r1", "suspect"), ("r1", "healthy")]
        assert router.state_of("r1") is sv.ReplicaState.HEALTHY
        assert router.replicas_healthy == 3

    def test_wedge_watchdog_death_resumes_mid_stream_bit_exact(
            self, fleet_engines, isolated_tokens):
        """WedgeReplica: the hung replica stops beating, the watchdog
        walks it SUSPECT→DEAD on the shared clock, and its mid-decode
        stream moves to a survivor by capture-resume — the served
        tokens are bit-identical to an unperturbed isolated run and
        the stream finishes `preempted-resumed` (full service)."""
        clk = sv.VirtualClock()
        router = _mk_fleet(
            fleet_engines, clk,
            config=sv.FleetConfig(suspect_after_s=0.5, dead_after_s=1.1))
        victim = sv.Request("v", _prompt(50), max_new_tokens=8)
        router.submit(victim)
        home = router.placement_of("v")
        for _ in range(3):                      # prefill + first decodes
            router.step()
            clk.advance(0.25)
        assert router.replica(home).phase_of("v").value == "decode"
        fault = WedgeReplica(home, at_step=0)
        with _EventTap() as tap:
            fault(0, router)
            for _ in range(8):
                router.step()
                clk.advance(0.25)
            results = router.run()
        assert fault.wedged
        assert router.state_of(home) is sv.ReplicaState.DEAD
        assert router.replicas_healthy == 2
        fo = tap.of("serving_fleet_failover")
        assert [(e["rid"], e["mode"]) for e in fo] \
            == [("v", "capture-resume")]
        assert fo[0]["new_tokens"] > 0          # tokens moved, not redone
        rs = tap.of("serving_fleet_resumed")
        assert [(e["rid"], e["mode"]) for e in rs] \
            == [("v", "capture-resume")]
        assert rs[0]["replica"] != home
        assert results["v"].finish_reason == "preempted-resumed"
        assert results["v"].tokens == isolated_tokens(victim)
        assert router.fleet_stats["resumed"] == 1

    def test_kill_requeues_and_replays_deterministically(
            self, fleet_engines, isolated_tokens):
        """A hard kill loses the device cache: the victim re-queues
        bare on a survivor and replays — the final token stream is
        still bit-identical to an uninterrupted run."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        victim = sv.Request("k", _prompt(60), max_new_tokens=6)
        router.submit(victim)
        home = router.placement_of("k")
        for _ in range(3):
            router.step()
            clk.advance(0.25)
        with _EventTap() as tap:
            router.kill(home)
            router.kill(home)                   # idempotent on DEAD
            results = router.run()
        fo = tap.of("serving_fleet_failover")
        assert [(e["rid"], e["mode"]) for e in fo] == [("k", "requeue")]
        assert results["k"].finish_reason == "length"
        assert results["k"].tokens == isolated_tokens(victim)
        assert router.state_of(home) is sv.ReplicaState.DEAD
        # the dead scheduler was closed; a rebuilt one replaces it
        fresh = sv.ContinuousBatchingScheduler(
            router.replica(home).engine, max_queue=8,
            log_interval=10 ** 9, clock=clk)
        with pytest.raises(ValueError, match="replace"):
            router.rejoin(home)
        router.replace(home, fresh)
        assert router.state_of(home) is sv.ReplicaState.HEALTHY
        assert router.replicas_healthy == 3

    def test_drain_moves_streams_then_rejoin(self, fleet_engines,
                                             isolated_tokens):
        """The rolling-reload hook: drain() moves a replica's live
        streams to survivors (capture-resume on dense), leaves it open
        and empty for an idle reload, and rejoin() returns it to
        placement eligibility."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        reqs = [sv.Request(f"d{i}", _prompt(70 + i), max_new_tokens=6)
                for i in range(2)]
        for r in reqs:
            router.submit(r)
        for _ in range(3):
            router.step()
            clk.advance(0.25)
        target = router.placement_of("d0")
        moved = router.drain(target)
        assert "d0" in moved
        assert router.state_of(target) is sv.ReplicaState.DRAINING
        assert router.replica(target).active_count == 0
        assert router.replica(target).queue_depth == 0
        # a draining replica takes no new placements
        router.submit(sv.Request("after", _prompt(79), max_new_tokens=2))
        assert router.placement_of("after") != target
        results = router.run()
        for r in reqs + [sv.Request("after", _prompt(79),
                                    max_new_tokens=2)]:
            assert results[r.rid].tokens == isolated_tokens(r)
        router.rejoin(target)
        assert router.state_of(target) is sv.ReplicaState.HEALTHY
        with pytest.raises(ValueError, match="no other healthy"):
            # draining every peer first would strand the streams
            for name in router.replica_names:
                router.drain(name)


# ---------------------------------------------------------------------------
# lifecycle edges (ISSUE 18 satellites): drain/watchdog interplay and
# the refusal paths a rolling upgrade leans on
# ---------------------------------------------------------------------------


class TestFleetLifecycleEdges:
    def test_draining_replica_survives_slow_reload(self, fleet_engines,
                                                   isolated_tokens):
        """The drain/watchdog audit: a DRAINING replica whose reload
        runs long (many missed beats, far past dead_after_s) is NEVER
        escalated SUSPECT→DEAD by its own drain — drain already
        evacuated it, and a watchdog kill would close the scheduler a
        reload is about to hand back."""
        clk = sv.VirtualClock()
        router = _mk_fleet(
            fleet_engines, clk,
            config=sv.FleetConfig(suspect_after_s=0.5, dead_after_s=1.0))
        req = sv.Request("sl", _prompt(200), max_new_tokens=6)
        router.submit(req)
        for _ in range(2):
            router.step()
            clk.advance(0.25)
        target = router.placement_of("sl")
        router.drain(target)
        with _EventTap() as tap:
            # a slow reload: the drained replica misses every beat for
            # 2.0s of clock — double dead_after_s
            for _ in range(8):
                router.stall(target)
                router.step()
                clk.advance(0.25)
        assert router.state_of(target) is sv.ReplicaState.DRAINING
        # no watchdog transition fired for it, and nothing was
        # evacuated a second time
        assert [e for e in tap.of("serving_fleet_replica_state")
                if e["replica"] == target] == []
        assert tap.of("serving_fleet_failover") == []
        router.rejoin(target)
        assert router.state_of(target) is sv.ReplicaState.HEALTHY
        # the rejoined replica serves again and the drained stream
        # finished unharmed elsewhere
        results = router.run()
        assert results["sl"].tokens == isolated_tokens(req)
        router.submit(sv.Request("post", _prompt(201), max_new_tokens=1))
        assert router.run()["post"].finish_reason in sv.SERVED_REASONS

    def test_rejoin_of_never_drained_replica_is_benign(
            self, fleet_engines, isolated_tokens):
        """rejoin() of a HEALTHY replica that was never drained: no
        state transition, no stream disturbed — just a beat+credit
        reset (the idempotent half of the rolling-reload pair)."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        req = sv.Request("rj", _prompt(210), max_new_tokens=6)
        router.submit(req)
        home = router.placement_of("rj")
        for _ in range(2):
            router.step()
            clk.advance(0.25)
        with _EventTap() as tap:
            router.rejoin(home)
        assert router.state_of(home) is sv.ReplicaState.HEALTHY
        assert tap.of("serving_fleet_replica_state") == []
        assert router.replica(home).active_count == 1   # untouched
        assert router.run()["rj"].tokens == isolated_tokens(req)

    def test_replace_of_live_replica_refused(self, fleet_engines,
                                             isolated_tokens):
        """replace() of a live replica is refused — silently swapping
        a live scheduler would drop its in-flight streams without a
        failover.  The fleet is untouched by the refusal."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        req = sv.Request("rp", _prompt(220), max_new_tokens=6)
        router.submit(req)
        home = router.placement_of("rp")
        for _ in range(2):
            router.step()
            clk.advance(0.25)
        original = router.replica(home)
        fresh = sv.ContinuousBatchingScheduler(
            original.engine, max_queue=8, log_interval=10 ** 9,
            clock=clk)
        with pytest.raises(ValueError, match="drain"):
            router.replace(home, fresh)
        # untouched: same scheduler object, same state, stream lives
        assert router.replica(home) is original
        assert router.state_of(home) is sv.ReplicaState.HEALTHY
        assert router.replica(home).active_count == 1
        assert router.run()["rp"].tokens == isolated_tokens(req)

    def test_drain_of_last_healthy_replica_refused_fleet_untouched(
            self, fleet_engines, isolated_tokens):
        """drain() of the last healthy replica must refuse (there is
        nowhere to move its streams) and leave the fleet untouched:
        the replica stays HEALTHY, its streams stay put, nothing is
        exported."""
        clk = sv.VirtualClock()
        router = _mk_fleet(fleet_engines, clk)
        req = sv.Request("lh", _prompt(230), max_new_tokens=6)
        router.submit(req)
        last = router.placement_of("lh")
        for _ in range(2):
            router.step()
            clk.advance(0.25)
        for name in router.replica_names:
            if name != last:
                router.drain(name)
        with _EventTap() as tap:
            with pytest.raises(ValueError, match="no other healthy"):
                router.drain(last)
        # untouched: still HEALTHY, stream still home, no export fired
        assert router.state_of(last) is sv.ReplicaState.HEALTHY
        assert router.placement_of("lh") == last
        assert router.replica(last).active_count == 1
        assert tap.of("serving_fleet_failover") == []
        assert [e for e in tap.of("serving_fleet_replica_state")
                if e["replica"] == last] == []
        assert router.run()["lh"].tokens == isolated_tokens(req)
        for name in router.replica_names:
            if name != last:
                router.rejoin(name)
        assert router.replicas_healthy == 3


# ---------------------------------------------------------------------------
# paged fleet teardown: a killed replica never leaks pins or blocks
# ---------------------------------------------------------------------------


def test_fleet_kill_releases_paged_blocks_and_pins(model, params,
                                                   isolated_tokens):
    """Fleet extension of the scheduler close() pin-leak regression: a
    killed *paged* replica's export + close derefs every cached pool
    block and unhooks the reclaim callback — nothing pins the dead
    pool — and the victim stream (paged capture cannot cross engines)
    re-queues on the survivor and replays bit-identically."""
    def paged_engine():
        return sv.DecodeEngine(
            model, params, slots=2, max_len=MAX, prefill_len=32,
            paged=sv.PagedCacheConfig(block_size=16, num_blocks=24))

    e0, e1 = paged_engine(), paged_engine()
    clk = sv.VirtualClock()
    router = _mk_fleet((e0, e1), clk, prefix=True)
    prompt = _prompt(80, n=40)
    warm = sv.Request("warm", prompt, max_new_tokens=2)
    router.submit(warm)
    home = router.placement_of("warm")
    router.run()
    eng = router.replica(home).engine
    assert eng.block_pool.used_blocks > 0       # cache holds pool refs
    assert eng.block_pool.reclaim is not None
    victim = sv.Request("vic", prompt, max_new_tokens=4)
    router.submit(victim)
    assert router.placement_of("vic") == home   # affinity
    for _ in range(2):
        router.step()
        clk.advance(0.25)
    with _EventTap() as tap:
        router.kill(home)
        results = router.run()
    # the dead replica's pool: every block released, reclaim unhooked
    assert eng.block_pool.used_blocks == 0
    assert eng.block_pool.reclaim is None
    # paged failover is always requeue (block bytes cannot cross pools)
    fo = tap.of("serving_fleet_failover")
    assert [(e["rid"], e["mode"]) for e in fo] == [("vic", "requeue")]
    assert results["vic"].tokens == isolated_tokens(victim)


# ---------------------------------------------------------------------------
# THE acceptance run: fleet chaos under overload
# ---------------------------------------------------------------------------


class TestFleetChaosAcceptance:
    N = 12
    KILL_STEP = 6

    def _workload(self):
        prompts = [_prompt(100 + i) for i in range(self.N)]
        # ~2x overload: all 12 arrive inside 1.5s of virtual time while
        # the 3x2-slot fleet needs several times that to serve them
        return sv.make_workload(prompts,
                                sv.uniform_arrivals(self.N, 8.0),
                                max_new_tokens=5, deadline_s=60.0,
                                rid_prefix="fl")

    def _run(self, engines, *, failover):
        for e in engines:
            e.reset()
        clk = sv.VirtualClock()
        scheds = {
            f"r{i}": sv.ContinuousBatchingScheduler(
                e, max_queue=8, log_interval=10 ** 9, clock=clk)
            for i, e in enumerate(engines)}
        router = sv.FleetRouter(
            scheds, config=sv.FleetConfig(failover=failover))
        fault = KillReplica("r0", at_step=self.KILL_STEP)
        wl = self._workload()
        with _EventTap() as tap:
            out = sv.LoadGenerator(router, wl, step_time_s=0.25,
                                   step_hook=fault).run()
        assert fault.killed
        return router, out, tap

    def test_kill_mid_stream_under_overload(self, fleet_engines,
                                            isolated_tokens):
        """Kill a replica mid-stream under 2x overload: every victim
        resumes on a survivor, zero admitted streams drop, every final
        token stream is bit-identical to its unperturbed isolated run,
        and fleet goodput strictly beats the no-failover fleet on the
        same chaos schedule."""
        obs.metrics.reset()
        wl = self._workload()
        router, out, tap = self._run(fleet_engines, failover=True)
        victims = {e["rid"] for e in tap.of("serving_fleet_failover")}
        assert victims                           # the kill hit live work
        # zero dropped: nothing rejected at submit, and every offered
        # request finished with FULL service
        assert out.rejected == []
        assert set(out.results) == {r.rid for r in wl.requests}
        for rid, res in out.results.items():
            assert res.finish_reason in sv.SERVED_REASONS, \
                f"{rid} dropped: {res.finish_reason}"
        # bit-exactness: every stream — victims included — matches its
        # unperturbed isolated reference
        for req in wl.requests:
            assert out.results[req.rid].tokens == isolated_tokens(req), \
                f"{req.rid} diverged after failover"
        assert router.replicas_healthy == 2
        g_failover = out.goodput
        assert g_failover is not None
        # the metrics surfaced: gauge tracks survivors, counters moved
        snap = obs.snapshot()
        healthy = snap["apex_serving_fleet_replicas_healthy"]["series"]
        assert healthy and healthy[0]["value"] == 2
        routed = snap["apex_serving_fleet_routed_total"]["series"]
        assert sum(s["value"] for s in routed) >= self.N
        fo_secs = snap["apex_serving_fleet_failover_seconds"]["series"]
        assert fo_secs and fo_secs[0]["count"] >= 1
        # no new program family on the failover path: decode compiled
        # exactly once per engine, the contract everywhere else
        for e in fleet_engines:
            assert e.decode_compiles() == 1

        # the honesty baseline: same workload, same chaos, no failover
        _, out0, tap0 = self._run(fleet_engines, failover=False)
        shed0 = {e["rid"] for e in tap0.of("serving_fleet_shed")}
        assert shed0                             # victims were dropped
        for rid in shed0:
            res = out0.results.get(rid)
            assert res is None or res.finish_reason \
                not in sv.SERVED_REASONS
        g_none = out0.goodput
        assert g_none is not None
        assert g_failover >= g_none + 0.1, \
            f"failover goodput {g_failover} vs no-failover {g_none}"

    @pytest.mark.slow   # ~5 s: tier-1 keeps the dense chaos acceptance
    # run above (the gate) — this is its tp=2 composition variant
    def test_kill_mid_stream_tp2_token_identical(self, model, params,
                                                 isolated_tokens):
        """The tp=2 variant: a 2-replica tp fleet loses one replica
        mid-stream; the victim replays on the survivor and the served
        stream is token-identical to the single-chip isolated run (the
        documented ~2.5e-7 psum drift is argmax-tier — it never moves
        a greedy token)."""
        engines = tuple(
            sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                            prefill_len=32, tp=TPConfig(size=2))
            for _ in range(2))
        clk = sv.VirtualClock()
        router = _mk_fleet(engines, clk, max_queue=8)
        reqs = [sv.Request(f"t{i}", _prompt(120 + i), max_new_tokens=5)
                for i in range(4)]
        for r in reqs:
            router.submit(r)
        for _ in range(3):
            router.step()
            clk.advance(0.25)
        victim_home = router.placement_of("t0")
        with _EventTap() as tap:
            router.kill(victim_home)
            results = router.run()
        assert tap.of("serving_fleet_failover")
        for r in reqs:
            assert results[r.rid].finish_reason in sv.SERVED_REASONS
            assert results[r.rid].tokens == isolated_tokens(r), \
                f"{r.rid} diverged from the single-chip reference"
        for e in engines:
            assert e.decode_compiles() == 1
