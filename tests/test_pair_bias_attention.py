"""Pair-bias flash attention kernel parity (ops/pair_bias_attention.py).

Values and all four gradients (dq, dk, dv, dbias — dbias reduces over the
broadcast MSA-row dim) must match the materialized reference, with and
without a kv mask, including fully-masked rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pair_bias_attention import (
    pair_bias_flash_attention,
    pair_bias_reference,
)


@pytest.fixture(autouse=True)
def _interpret_kernels(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "interpret")
    yield


def _inputs(rng, r=3, b=2, h=2, s=128, d=32, dtype=jnp.float32,
            with_mask=False):
    R = r * b
    q = jnp.asarray(rng.standard_normal((R, h, s, d)) * 0.5, dtype)
    k = jnp.asarray(rng.standard_normal((R, h, s, d)) * 0.5, dtype)
    v = jnp.asarray(rng.standard_normal((R, h, s, d)) * 0.5, dtype)
    bias = jnp.asarray(rng.standard_normal((b, h, s, s)) * 0.5, dtype)
    mask = None
    if with_mask:
        m = rng.random((R, s)) > 0.2
        m[0, :] = False          # one fully-masked row batch entry
        mask = jnp.asarray(m)
    return q, k, v, bias, mask


@pytest.mark.parametrize("with_mask", [False, True])
def test_forward_matches_reference(rng, with_mask):
    q, k, v, bias, mask = _inputs(rng, with_mask=with_mask)
    out = pair_bias_flash_attention(q, k, v, bias, mask, block_q=64,
                                    block_k=64)
    ref = pair_bias_reference(q, k, v, bias, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    if with_mask:
        np.testing.assert_array_equal(np.asarray(out[0]), 0.0)


@pytest.mark.parametrize("with_mask", [False, True])
def test_gradients_match_reference(rng, with_mask):
    q, k, v, bias, mask = _inputs(rng, with_mask=with_mask)
    do = jnp.asarray(rng.standard_normal(q.shape), q.dtype)

    def loss_flash(q, k, v, bias):
        y = pair_bias_flash_attention(q, k, v, bias, mask, block_q=64,
                                      block_k=64)
        return jnp.sum(y.astype(jnp.float32) * do.astype(jnp.float32))

    def loss_ref(q, k, v, bias):
        y = pair_bias_reference(q, k, v, bias, mask)
        return jnp.sum(y.astype(jnp.float32) * do.astype(jnp.float32))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for name, a, b_ in zip("q k v bias".split(), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_bf16_runs(rng):
    q, k, v, bias, mask = _inputs(rng, dtype=jnp.bfloat16)
    out = pair_bias_flash_attention(q, k, v, bias, mask, block_q=64,
                                    block_k=64)
    ref = pair_bias_reference(q, k, v, bias, mask)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_openfold_attention_core_routes_through_kernel(rng):
    """The 5-D openfold entrypoint must dispatch to the Pallas kernel for
    long sequences (s >= 1024 — below that the measured winner is the
    materialized XLA path and routing must NOT engage) and match the
    materialized semantics."""
    from apex_tpu.contrib.openfold_triton import attention_core

    b, r, h, s, d = 1, 2, 1, 1024, 8
    q = jnp.asarray(rng.standard_normal((b, r, h, s, d)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, r, h, s, d)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, r, h, s, d)) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((b, 1, h, s, s)) * 0.3,
                       jnp.float32)
    mask = jnp.asarray(rng.random((b, r, 1, 1, s)) > 0.1)

    out = attention_core(q, k, v, mask=mask, bias=bias)
    jaxpr = str(jax.make_jaxpr(
        lambda *a: attention_core(a[0], a[1], a[2], mask=a[3], bias=a[4]))(
        q, k, v, mask, bias))
    assert "pallas" in jaxpr or "custom_vjp" in jaxpr

    # reference semantics: materialized softmax with -inf mask fill
    sc = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32)
    sc = sc + bias.astype(jnp.float32)
    sc = jnp.where(mask.astype(bool), sc, -1e9)
    probs = jax.nn.softmax(sc, axis=-1)
    ref = jnp.einsum("...qk,...kd->...qd", probs.astype(q.dtype), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
