"""The API reference (VERDICT r3 item 7) must exist and cover the key
packages — the markdown analog of the reference's sphinx tree building
cleanly (`/root/reference/docs/source/index.rst` coverage)."""

from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DOCS = REPO / "docs"

PAGES = ["amp", "optimizers", "parallel", "transformer", "normalization",
         "layers", "ops", "models", "contrib", "resilience", "serving",
         "observability", "utils"]

# page -> symbols a user would look up there (spot checks that the
# generator actually rendered the module contents, not empty shells)
MUST_MENTION = {
    "amp": ["initialize", "LossScaler"],
    "optimizers": ["FusedAdam", "FusedLAMB", "DistributedFusedAdam"],
    "parallel": ["DistributedDataParallel", "SyncBatchNorm", "LARC"],
    "transformer": ["ColumnParallelLinear", "vocab_parallel_cross_entropy",
                    "ring_attention", "ExpertParallelMLP"],
    "normalization": ["FusedLayerNorm", "FusedRMSNorm"],
    "ops": ["flash_attention", "fused_lm_head_loss"],
    # vit_l16/llama2_7b are @classmethod constructors — they pin the
    # classmethod-rendering path of the generator
    "models": ["LlamaForCausalLM", "ViTConfig", "build_llama_pipeline",
               "vit_l16", "llama2_7b"],
    "contrib": ["SoftmaxCrossEntropyLoss", "FocalLoss", "Transducer"],
    "serving": ["DecodeEngine", "ContinuousBatchingScheduler",
                "load_serving_params", "cache_utilization",
                "LoadGenerator", "burst_arrivals", "OpenLoopWorkload",
                "schedule_fingerprint"],
    # the prologue (naming conventions + metric inventory + span
    # semantics) plus the introspected API must both be present
    "observability": ["MetricsRegistry", "Histogram", "prometheus_text",
                      "TraceRecorder", "recording", "profile_on_stall",
                      "apex_step_duration_seconds", "apex_serving_ttft_seconds",
                      "add_event_sink", "LATENCY_BUCKETS_S", "le=",
                      "traceEvents",
                      # ISSUE-12: request traces + SLO reports
                      "RequestTraceRecorder", "build_report",
                      "crosscheck_quantiles", "export_jsonl",
                      "apex_serving_queue_wait_seconds",
                      "apex_serving_goodput_ratio"],
    # the prologue (checkpoint format / recovery semantics / supervisor
    # sections) plus the introspected API must both be present
    "resilience": ["CheckpointManager", "FaultInjector", "make_guarded_step",
                   "manifest.json", "crc32", "SimulatedPreemption",
                   "StepWatchdog", "TrainingSupervisor", "retry_transient",
                   "GuardedIterator", "heartbeat", "FlakyIterator"],
    "utils": ["tree_to_host_dict", "emit_event"],
}


def test_index_exists_and_links_all_pages():
    index = (DOCS / "index.md").read_text()
    for page in PAGES:
        assert f"api/{page}.md" in index, f"index.md missing link to {page}"


def test_pages_exist_and_cover_key_symbols():
    for page in PAGES:
        path = DOCS / "api" / f"{page}.md"
        assert path.exists(), f"missing docs/api/{page}.md"
        text = path.read_text()
        assert len(text) > 500, f"{page}.md suspiciously small"
        assert "IMPORT FAILED" not in text, f"{page}.md has import failures"
        for sym in MUST_MENTION.get(page, []):
            assert sym in text, f"{page}.md does not document {sym}"
