"""contrib small kernels: index_mul_2d, conv_bias_relu, GBN/bnp batch norms.

Oracles: torch CPU ops (conv2d/batch_norm) and direct numpy math, mirroring
the reference contrib tests (apex/contrib/test/index_mul_2d, conv_bias_relu).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map


# ---------------------------------------------------------------------------
# index_mul_2d
# ---------------------------------------------------------------------------

def test_index_mul_2d_forward_backward():
    from apex_tpu.contrib.index_mul_2d import index_mul_2d

    rng = np.random.default_rng(0)
    S, N, H = 10, 32, 16
    in1 = jnp.asarray(rng.standard_normal((S, H)), jnp.float32)
    in2 = jnp.asarray(rng.standard_normal((N, H)), jnp.float32)
    idx = jnp.asarray(rng.integers(0, S, N))

    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(out, np.asarray(in1)[np.asarray(idx)] * in2,
                               rtol=1e-6)

    # custom backward vs autodiff of the unfused expression
    def fused(a, b):
        return (index_mul_2d(a, b, idx) ** 2).sum()

    def unfused(a, b):
        return ((jnp.take(a, idx, axis=0) * b) ** 2).sum()

    g1 = jax.grad(fused, argnums=(0, 1))(in1, in2)
    g2 = jax.grad(unfused, argnums=(0, 1))(in1, in2)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_index_mul_2d_validation():
    from apex_tpu.contrib.index_mul_2d import index_mul_2d

    with pytest.raises(ValueError):
        index_mul_2d(jnp.zeros((2, 3, 4)), jnp.zeros((2, 3)), jnp.zeros(2, jnp.int32))
    with pytest.raises(ValueError):
        index_mul_2d(jnp.zeros((2, 3)), jnp.zeros((4, 3)),
                     jnp.zeros(2, jnp.int32))


# ---------------------------------------------------------------------------
# conv_bias_relu
# ---------------------------------------------------------------------------

def _torch_conv(x_nhwc, w_hwio, bias, padding, stride):
    import torch

    x = torch.from_numpy(np.moveaxis(x_nhwc, -1, 1).copy())
    w = torch.from_numpy(np.transpose(w_hwio, (3, 2, 0, 1)).copy())
    y = torch.nn.functional.conv2d(x, w, torch.from_numpy(bias),
                                   stride=stride, padding=padding)
    return np.moveaxis(y.numpy(), 1, -1)


@pytest.mark.parametrize("padding,stride", [(0, 1), (1, 2)])
def test_conv_bias_relu_matches_torch(padding, stride):
    from apex_tpu.contrib.conv_bias_relu import ConvBiasReLU

    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 3, 8)).astype(np.float32)
    b = rng.standard_normal(8).astype(np.float32)

    got = ConvBiasReLU(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                       padding, stride)
    want = np.maximum(_torch_conv(x, w, b, padding, stride), 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_conv_bias_mask_relu_and_frozen_scale():
    from apex_tpu.contrib.conv_bias_relu import (ConvBiasMaskReLU,
                                                 ConvFrozenScaleBiasReLU)

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((1, 6, 6, 4)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 4, 8)), jnp.float32)
    b = jnp.asarray(rng.standard_normal(8), jnp.float32)
    scale = jnp.asarray(rng.standard_normal(8), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (1, 6, 6, 8)), jnp.float32)

    y = ConvBiasMaskReLU(x, w, b, mask, 1, 1)
    assert y.shape == (1, 6, 6, 8)
    assert float(jnp.min(y)) >= 0.0
    assert np.all(np.asarray(y)[np.asarray(mask) == 0] == 0.0)

    # frozen scale/bias must carry no gradient
    g = jax.grad(lambda s: ConvFrozenScaleBiasReLU(x, w, s, b, 1, 1).sum())(scale)
    assert np.all(np.asarray(g) == 0.0)
    gw = jax.grad(lambda w: ConvFrozenScaleBiasReLU(x, w, scale, b, 1, 1).sum())(w)
    assert np.abs(np.asarray(gw)).max() > 0.0


# ---------------------------------------------------------------------------
# GroupBatchNorm2d (cudnn_gbn) / BatchNorm2d_NHWC (groupbn)
# ---------------------------------------------------------------------------

def _bn_oracle(x, eps=1e-5):
    m = x.mean(axis=(0, 1, 2))
    v = x.var(axis=(0, 1, 2))
    return (x - m) / np.sqrt(v + eps)


def test_group_batch_norm_subgroup_stats():
    """With bn_group=4 on an 8-rank axis, ranks 0-3 and 4-7 form separate
    stat groups — feed different distributions to each half and check each
    half is normalized by its own stats."""
    from apex_tpu.contrib.cudnn_gbn import (GroupBatchNorm2d,
                                            bn_group_index_groups)

    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ("dp",))
    C = 8
    rng = np.random.default_rng(3)
    # global batch 8 (1/rank); first half shifted by +10
    x = rng.standard_normal((8, 4, 4, C)).astype(np.float32)
    x[:4] += 10.0

    bn = GroupBatchNorm2d(num_features=C, axis_name="dp",
                          axis_index_groups=bn_group_index_groups(8, 4),
                          momentum=0.0)
    params = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]))

    def fn(x):
        y, _ = bn.apply(params, x, mutable=["batch_stats"])
        return y

    with mesh:
        y = jax.jit(shard_map(fn, mesh=mesh, in_specs=P("dp"),
                              out_specs=P("dp"), **NO_REP_CHECK))(
            jnp.asarray(x))

    y = np.asarray(y)
    np.testing.assert_allclose(y[:4], _bn_oracle(x[:4]), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(y[4:], _bn_oracle(x[4:]), rtol=2e-3, atol=2e-3)
    # cross-check: whole-world stats would NOT normalize the halves
    assert abs(_bn_oracle(x)[:4].mean()) > 0.5


def test_batchnorm_nhwc_addrelu():
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((4, 5, 5, 8)), jnp.float32)
    z = jnp.asarray(rng.standard_normal((4, 5, 5, 8)), jnp.float32)

    bn = BatchNorm2d_NHWC(num_features=8, fuse_relu=True)
    params = bn.init(jax.random.PRNGKey(0), x)
    y, _ = bn.apply(params, x, z, mutable=["batch_stats"])
    want = np.maximum(_bn_oracle(np.asarray(x)) + np.asarray(z), 0.0)
    np.testing.assert_allclose(y, want, rtol=2e-3, atol=2e-3)

    # passing z selects the reference's bn_addrelu kernel, which applies
    # ReLU even with fuse_relu=False
    bn2 = BatchNorm2d_NHWC(num_features=8, fuse_relu=False)
    y2, _ = bn2.apply(bn2.init(jax.random.PRNGKey(0), x), x, z,
                      mutable=["batch_stats"])
    np.testing.assert_allclose(y2, want, rtol=2e-3, atol=2e-3)


def test_bn_group_index_groups_validation():
    from apex_tpu.contrib.cudnn_gbn import bn_group_index_groups

    assert bn_group_index_groups(8, 1) is None
    assert bn_group_index_groups(8, 4) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    with pytest.raises(ValueError):
        bn_group_index_groups(6, 4)
