"""L1-tier convergence tests (the reference's tests/L1 analog, shrunk to
CI size): opt_level × loss_scale cross-product vs the O0 baseline, and
end-to-end checkpoint save/resume."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                       / "examples" / "imagenet"))

from main import run_training  # noqa: E402
from run_convergence import count_scaler_skips  # noqa: E402

TINY = dict(arch="resnet10", steps=8, image_size=32, batch_size=8,
            num_classes=10, lr=0.05, verbose=False)


@pytest.fixture(scope="module")
def o0_trace():
    return run_training(opt_level="O0", **TINY)["losses"]


# CI-sized slice of the cross-product: one combo per distinct code path
# (O1 bf16 cast-lists, O2 fp16 dynamic scaler, O3 pure-half); the full
# 12-combo sweep lives in examples/imagenet/run_convergence.py
@pytest.mark.parametrize("opt_level,loss_scale,half", [
    ("O1", None, "bf16"),
    # ~24 s: the fp16 dynamic-scaler path keeps tier-1 witnesses in
    # test_amp.py / test_loss_scale.py; O1+O3 cover the trace claim
    pytest.param("O2", "dynamic", "fp16", marks=pytest.mark.slow),
    ("O3", None, "bf16"),
])
def test_policy_trace_matches_o0(o0_trace, opt_level, loss_scale, half):
    trace = run_training(opt_level=opt_level, loss_scale=loss_scale,
                         half=half, **TINY)["losses"]
    assert len(trace) == len(o0_trace)
    assert trace[-1] < trace[0], "loss did not decrease"
    # dynamic scaling backs off from 65536 by skipping the first step(s);
    # the trajectory is the O0 one delayed by the skip count (the L0 amp
    # tests pin the same behavior for the reference's dynamic scaler)
    skips = count_scaler_skips(trace)
    np.testing.assert_allclose(trace[skips:],
                               o0_trace[:len(o0_trace) - skips],
                               rtol=0.2, atol=0.35)


@pytest.mark.slow   # ~16 s: tier-1 keeps the checkpoint round-trip
# witnesses in test_resilience.py and the remaining convergence cells
def test_checkpoint_save_resume_trace_continues(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    full = run_training(opt_level="O2", **TINY)["losses"]

    first = run_training(opt_level="O2", save=ckpt, **{**TINY, "steps": 4})
    resumed = run_training(opt_level="O2", resume=ckpt,
                           **{**TINY, "steps": 8})
    trace = first["losses"] + resumed["losses"]
    # the resumed run continues the continuous trajectory exactly
    np.testing.assert_allclose(trace, full, rtol=1e-4, atol=1e-5)
