"""Test harness: single-host multi-device mesh on CPU.

TPU translation of the reference's ``MultiProcessTestCase``-style single-host
multi-rank testing (apex/transformer/testing/distributed_test_base.py:22-82):
instead of spawning processes, we force 8 virtual CPU devices and build real
``jax.sharding.Mesh``es over them (SURVEY.md §4 "TPU translation").

This file must run before jax initializes its backends, hence env mutation at
import time.
"""

import os

# Force CPU even when the ambient environment selects a TPU plugin
# (JAX_PLATFORMS=axon): the suite's multi-rank tests need 8 virtual devices.
# The axon sitecustomize imports jax at interpreter start, freezing the env's
# JAX_PLATFORMS into jax.config — so update the config, not the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite is compile-dominated (tiny models,
# big shard_map graphs); caching jit artifacts across runs cuts wall time
# from >13 min to the actual execution cost.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 forced CPU devices, got {len(devs)}"
    return devs


@pytest.fixture
def mesh8(devices):
    """A 1-D 8-device mesh named ('dp',)."""
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:8]), ("dp",))


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
