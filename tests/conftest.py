"""Test harness: single-host multi-device mesh on CPU.

TPU translation of the reference's ``MultiProcessTestCase``-style single-host
multi-rank testing (apex/transformer/testing/distributed_test_base.py:22-82):
instead of spawning processes, we force 8 virtual CPU devices and build real
``jax.sharding.Mesh``es over them (SURVEY.md §4 "TPU translation").

This file must run before jax initializes its backends, hence env mutation at
import time.
"""

import os

# Force CPU even when the ambient environment selects a TPU plugin
# (JAX_PLATFORMS=axon): the suite's multi-rank tests need 8 virtual devices.
# The axon sitecustomize imports jax at interpreter start, freezing the env's
# JAX_PLATFORMS into jax.config — so update the config, not the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# NO persistent compilation cache.  The warm-cache snapshot this suite
# used to ship (tests/.jax_cache, wired here with min_compile_time 0.5 s)
# is a correctness hazard on jaxlib 0.4.37 CPU: deserializing a cached
# executable — including one written moments earlier by the SAME suite
# process — nondeterministically dies with SIGSEGV/SIGABRT inside XLA
# (reproduced on the DDP ResNet train_step of test_convergence_l1, which
# aborted the entire tier-1 run at file 7/41 on this host).  A compile
# cache that can kill the process is worse than cold compiles; the
# resilience PR removed it.  If a future jaxlib fixes executable
# deserialization, re-enable via jax_compilation_cache_dir here and
# re-commit a snapshot built on the SAME host image.
import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs -m 'not slow' (ROADMAP.md); register the marker so
    # slow-marked long benchmarks don't trip UnknownMarkWarning
    config.addinivalue_line(
        "markers",
        "slow: long-running test excluded from tier-1 (-m 'not slow')")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 forced CPU devices, got {len(devs)}"
    return devs


@pytest.fixture
def mesh8(devices):
    """A 1-D 8-device mesh named ('dp',)."""
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:8]), ("dp",))


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
