"""Training-resilience subsystem tests.

Every recovery path is exercised here rather than discovered in
production (ISSUE 1 tentpole): validated atomic checkpointing with
corruption fallback, deterministic fault injection, anomaly-aware
guarded stepping, cross-microbatch skip consistency, state round-trips
for every amp/optimizer state type, and the end-to-end acceptance run —
kill mid-run, corrupt the newest checkpoint, restart, resume
bit-identically.
"""

import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, resilience as rz
from apex_tpu._logging import emit_event
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedMixedPrecisionLamb,
    FusedNovoGrad,
    FusedSGD,
)
from apex_tpu.resilience.checkpoint import _TMP_PREFIX


def _tree_equal(a, b):
    from apex_tpu.utils.serialization import leaf_to_numpy

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = leaf_to_numpy(x), leaf_to_numpy(y)
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y)


def _state_tree(seed=0):
    """A representative train-state pytree: mixed dtypes, NamedTuple
    optimizer state, scaler state, old- and new-style RNG keys, counter."""
    params = {"w": jnp.arange(12, dtype=jnp.bfloat16).reshape(3, 4),
              "b": jnp.ones((4,), jnp.float32) * seed}
    opt = FusedAdam(lr=1e-2, master_weights=True)
    scaler = LossScaler()
    return {
        "params": params,
        "opt": opt.init(params),
        "scaler": scaler.init(),
        "guard": rz.init_guard_state(scaler),
        "rng_old": jax.random.PRNGKey(seed),
        "rng_typed": jax.random.key(seed),
        "step": jnp.int32(seed),
    }


# --------------------------------------------------------------------------
# validated atomic checkpointing
# --------------------------------------------------------------------------

class TestCheckpoint:
    def test_roundtrip_bit_identical(self, tmp_path):
        tree = _state_tree(3)
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        mgr.save(7, tree)
        restored, step = mgr.restore(like=_state_tree(0))
        assert step == 7
        _tree_equal(tree, restored)

    def test_rotation_keeps_last_k(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=2)
        tree = _state_tree()
        for s in range(5):
            mgr.save(s, tree)
        assert mgr.all_steps() == [3, 4]

    def test_no_temp_litter_after_save(self, tmp_path):
        rz.save_checkpoint(str(tmp_path), 1, _state_tree())
        leftovers = [n for n in os.listdir(tmp_path)
                     if n.startswith(_TMP_PREFIX)]
        assert leftovers == []

    def test_corruption_detected_and_skipped(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        tree = _state_tree()
        for s in range(3):
            mgr.save(s, tree)
        rz.FaultInjector(rz.FaultPlan(seed=5)).corrupt_checkpoint(
            mgr.checkpoint_path(2))
        with pytest.raises(rz.CheckpointError, match="CRC"):
            rz.validate_checkpoint(mgr.checkpoint_path(2))
        assert mgr.latest_valid_step() == 1
        _, step = mgr.restore(like=_state_tree())
        assert step == 1

    def test_truncation_detected_and_skipped(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        tree = _state_tree()
        for s in range(2):
            mgr.save(s, tree)
        rz.FaultInjector(rz.FaultPlan()).truncate_checkpoint(
            mgr.checkpoint_path(1), drop_bytes=3)
        with pytest.raises(rz.CheckpointError, match="truncated"):
            rz.validate_checkpoint(mgr.checkpoint_path(1))
        _, step = mgr.restore(like=_state_tree())
        assert step == 0

    def test_corrupt_but_parsable_manifest_falls_back(self, tmp_path):
        """Bit corruption in manifest.json itself (still valid JSON) must
        surface as CheckpointError and fall back — never escape as a
        ValueError that aborts the restore walk (code-review finding)."""
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        tree = _state_tree()
        for s in range(2):
            mgr.save(s, tree)
        mpath = os.path.join(mgr.checkpoint_path(1), "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["leaves"][0]["nbytes"] -= 1  # size-consistent lie
        manifest["data_nbytes"] -= 1
        blob = json.dumps(manifest)
        with open(os.path.join(mgr.checkpoint_path(1), "data.bin"),
                  "r+b") as f:
            f.truncate(manifest["data_nbytes"])
        with open(mpath, "w") as f:
            f.write(blob)
        _, step = mgr.restore(like=_state_tree())
        assert step == 0
        # non-dict manifest: also a clean rejection
        with open(mpath, "w") as f:
            f.write("[1, 2, 3]")
        _, step = mgr.restore(like=_state_tree())
        assert step == 0

    def test_resave_existing_step_stays_valid(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        tree = _state_tree(1)
        mgr.save(5, tree)
        mgr.save(5, _state_tree(2))  # replace in place
        restored, step = mgr.restore(like=_state_tree(0))
        assert step == 5
        _tree_equal(restored, _state_tree(2))
        leftovers = [n for n in os.listdir(tmp_path)
                     if n.startswith(_TMP_PREFIX)]
        assert leftovers == []

    def test_unreadable_manifest_skipped(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        tree = _state_tree()
        for s in range(2):
            mgr.save(s, tree)
        with open(os.path.join(mgr.checkpoint_path(1), "manifest.json"),
                  "w") as f:
            f.write("{not json")
        _, step = mgr.restore(like=_state_tree())
        assert step == 0

    def test_all_invalid_raises(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=3)
        mgr.save(0, _state_tree())
        rz.FaultInjector(rz.FaultPlan()).corrupt_checkpoint(
            mgr.checkpoint_path(0))
        with pytest.raises(rz.CheckpointError, match="no valid checkpoint"):
            mgr.restore(like=_state_tree())

    def test_empty_root_raises(self, tmp_path):
        with pytest.raises(rz.CheckpointError):
            rz.restore_checkpoint(str(tmp_path / "nothing"), like={})

    def test_structure_mismatch_rejected(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, {"w": jnp.ones((3,))})
        with pytest.raises(rz.CheckpointError, match="template"):
            mgr.restore(like={"w": jnp.ones((4,))}, step=0)
        with pytest.raises(rz.CheckpointError, match="no leaf"):
            mgr.restore(like={"v": jnp.ones((3,))}, step=0)

    def test_superset_checkpoint_rejected(self, tmp_path):
        """A checkpoint with leaves the template dropped (structure
        drift) must be rejected, not silently partially restored."""
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, {"w": jnp.ones((3,)), "legacy": jnp.ones((2,))})
        with pytest.raises(rz.CheckpointError, match="template does not"):
            mgr.restore(like={"w": jnp.ones((3,))}, step=0)

    def test_structural_mismatches_name_offending_keystr(self, tmp_path):
        """ISSUE 3 satellite: every restore_checkpoint structural-
        mismatch path — wrong leaf shape, wrong dtype, missing leaf,
        extra leaf — raises CheckpointError NAMING the offending keystr
        (an operator fixing a template needs the leaf, not a diff)."""
        rz.save_checkpoint(str(tmp_path), 0,
                           {"w": jnp.ones((3, 2), jnp.float32),
                            "b": jnp.zeros((4,), jnp.float32)})
        good_b = jnp.zeros((4,), jnp.float32)
        with pytest.raises(rz.CheckpointError,
                           match=r"\['w'\].*template wants float32\[3, 3\]"):
            rz.restore_checkpoint(
                str(tmp_path), {"w": jnp.ones((3, 3), jnp.float32),
                                "b": good_b}, step=0)
        with pytest.raises(rz.CheckpointError,
                           match=r"\['w'\].*template wants bfloat16"):
            rz.restore_checkpoint(
                str(tmp_path), {"w": jnp.ones((3, 2), jnp.bfloat16),
                                "b": good_b}, step=0)
        with pytest.raises(rz.CheckpointError,
                           match=r"no leaf \"\['v'\]\""):
            rz.restore_checkpoint(
                str(tmp_path), {"v": jnp.ones((3, 2), jnp.float32),
                                "b": good_b}, step=0)
        with pytest.raises(rz.CheckpointError,
                           match=r"template does not.*\['w'\]"):
            rz.restore_checkpoint(str(tmp_path), {"b": good_b}, step=0)

    def test_pinned_step_restore(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path), keep=5)
        for s in range(3):
            mgr.save(s, {"x": jnp.float32(s)})
        restored, step = mgr.restore(like={"x": jnp.float32(0)}, step=1)
        assert step == 1 and float(restored["x"]) == 1.0

    def test_rotation_never_deletes_just_written_step(self, tmp_path):
        """An undetected-corrupt newer dir occupying the keep window must
        not cause rotation to delete the checkpoint just written — the
        recoverable set can only grow on save (code-review finding)."""
        tree = _state_tree()
        mgr3 = rz.CheckpointManager(str(tmp_path), keep=3)
        for s in (40, 41, 42):
            mgr3.save(s, tree)
        rz.FaultInjector(rz.FaultPlan(seed=2)).corrupt_checkpoint(
            mgr3.checkpoint_path(42))  # CRC-corrupt, size intact
        mgr = rz.CheckpointManager(str(tmp_path), keep=1)
        _, resumed = mgr.restore(like=_state_tree())  # falls back to 41
        assert resumed == 41
        mgr.save(41, tree)  # resumed run re-saves its current step under keep=1
        assert 41 in mgr.all_steps()
        assert mgr.latest_valid_step() == 41  # never left unrecoverable

    def test_rotation_drops_structurally_broken_dirs_first(self, tmp_path):
        """Truncated checkpoints must not count toward ``keep``."""
        mgr = rz.CheckpointManager(str(tmp_path), keep=2)
        tree = _state_tree()
        for s in range(3):
            mgr.save(s, tree)
        rz.FaultInjector(rz.FaultPlan()).truncate_checkpoint(
            mgr.checkpoint_path(2), drop_bytes=5)
        mgr.save(3, tree)  # rotation: broken 2 dropped, valid 1+3 kept
        assert 2 not in mgr.all_steps()
        assert {1, 3} <= set(mgr.all_steps())

    def test_restore_preserves_template_sharding(self, tmp_path, mesh8):
        """Restoring a sharded state must land the leaves on the
        template's sharding, not collapse them to the default device."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh8, P("dp"))
        leaf = jax.device_put(jnp.arange(16, dtype=jnp.float32), sharding)
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, {"w": leaf})
        restored, _ = mgr.restore(like={"w": leaf})
        assert restored["w"].sharding == sharding
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(16, dtype=np.float32))

    def test_orphaned_tmp_dirs_swept_on_next_save(self, tmp_path):
        """A hard kill mid-save leaves a tmp_* dir; the next save must
        sweep it so repeated preemptions cannot fill the disk."""
        orphan = tmp_path / "tmp_dead_writer"
        orphan.mkdir(parents=True)
        (orphan / "data.bin").write_bytes(b"\0" * 64)
        rz.save_checkpoint(str(tmp_path), 0, _state_tree())
        assert not orphan.exists()
        assert rz.latest_valid_step(str(tmp_path)) == 0

    def test_manifest_is_auditable_without_jax(self, tmp_path):
        """The format contract: plain JSON manifest + raw bytes, no pickle."""
        path = rz.save_checkpoint(str(tmp_path), 0, {"w": jnp.ones((2, 2))})
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        [rec] = manifest["leaves"]
        assert rec["shape"] == [2, 2] and rec["dtype"] == "float32"
        raw = open(os.path.join(path, "data.bin"), "rb").read()
        np.testing.assert_array_equal(
            np.frombuffer(raw, np.float32).reshape(2, 2), np.ones((2, 2)))


# --------------------------------------------------------------------------
# fault injection
# --------------------------------------------------------------------------

class TestManifestMeshMetadata:
    """ISSUE 3 satellite: every manifest stamps format_version plus the
    saving mesh's shape/world sizes, and a v1 (whole-tree) checkpoint
    refuses to restore onto a DIFFERENT mesh instead of silently
    resharding wrong.  Pre-ISSUE-3 manifests (no mesh key) still load."""

    def test_manifest_stamps_version_and_mesh(self, tmp_path, devices):
        from apex_tpu.transformer import parallel_state

        parallel_state.initialize_model_parallel(2, devices=devices[:8])
        try:
            path = rz.save_checkpoint(str(tmp_path), 0, {"w": jnp.ones(3)})
        finally:
            parallel_state.destroy_model_parallel()
        with open(os.path.join(path, "manifest.json")) as f:
            man = json.load(f)
        assert man["format_version"] == 1
        assert man["mesh"]["axes"] == {"dp": 4, "pp": 1, "tp": 2}
        assert (man["mesh"]["dp"], man["mesh"]["pp"],
                man["mesh"]["tp"]) == (4, 1, 2)
        assert man["mesh"]["world"] == 8

    def test_v1_mismatched_mesh_restore_raises(self, tmp_path, devices):
        from apex_tpu.transformer import parallel_state

        parallel_state.initialize_model_parallel(2, devices=devices[:8])
        try:
            rz.save_checkpoint(str(tmp_path), 0, {"w": jnp.ones(3)})
        finally:
            parallel_state.destroy_model_parallel()
        # restart lands on a (dp=2, tp=4) slice: the whole-tree bytes
        # cannot reshard, so the restore must refuse loudly
        parallel_state.initialize_model_parallel(4, devices=devices[:8])
        try:
            with pytest.raises(rz.CheckpointError, match="cannot reshard"):
                rz.restore_checkpoint(str(tmp_path), {"w": jnp.ones(3)},
                                      step=0)
        finally:
            parallel_state.destroy_model_parallel()
        # back on the saving shape, the same checkpoint loads fine
        parallel_state.initialize_model_parallel(2, devices=devices[:8])
        try:
            _, step = rz.restore_checkpoint(str(tmp_path),
                                            {"w": jnp.ones(3)})
        finally:
            parallel_state.destroy_model_parallel()
        assert step == 0

    def test_legacy_manifest_without_mesh_still_loads(self, tmp_path,
                                                      devices):
        from apex_tpu.transformer import parallel_state

        path = rz.save_checkpoint(str(tmp_path), 0, {"w": jnp.ones(3)})
        mp = os.path.join(path, "manifest.json")
        with open(mp) as f:
            man = json.load(f)
        assert man["mesh"] is None  # no parallel_state at save time
        del man["mesh"]  # a pre-ISSUE-3 v1 manifest has no mesh key
        with open(mp, "w") as f:
            json.dump(man, f)
        parallel_state.initialize_model_parallel(2, devices=devices[:8])
        try:
            _, step = rz.restore_checkpoint(str(tmp_path),
                                            {"w": jnp.ones(3)})
        finally:
            parallel_state.destroy_model_parallel()
        assert step == 0


class TestFaultInjection:
    def test_grad_injection_is_step_targeted(self):
        inj = rz.FaultInjector(rz.FaultPlan(seed=0, nan_grad_steps=(3,),
                                            inf_grad_steps=(5,)))
        grads = {"a": jnp.ones((8,)), "b": jnp.ones((2, 2))}

        def nonfinite(t):
            return bool(jnp.any(jnp.asarray(
                [jnp.any(~jnp.isfinite(l)) for l in jax.tree.leaves(t)])))

        assert not nonfinite(inj.inject_grads(grads, jnp.int32(2)))
        assert nonfinite(inj.inject_grads(grads, jnp.int32(3)))
        assert nonfinite(inj.inject_grads(grads, jnp.int32(5)))
        clean = inj.inject_grads(grads, jnp.int32(0))
        _tree_equal(grads, clean)  # off-step injection is value-identical

    def test_grad_injection_deterministic_and_jittable(self):
        plan = rz.FaultPlan(seed=42, nan_grad_steps=(1,))
        grads = {"a": jnp.ones((16,)), "b": jnp.ones((4, 4))}
        out1 = rz.FaultInjector(plan).inject_grads(grads, jnp.int32(1))
        out2 = jax.jit(rz.FaultInjector(plan).inject_grads)(
            grads, jnp.int32(1))
        _tree_equal(out1, out2)  # same seed -> same fault placement

    def test_faults_only_target_float_leaves_without_dtype_roundtrip(self):
        """Integer leaves (step counters riding in a grads tree) must
        never host a NaN, and off-step execution must be value- AND
        dtype-identical for every leaf (no fp32 roundtrip)."""
        inj = rz.FaultInjector(rz.FaultPlan(seed=11, nan_grad_steps=(2,)))
        grads = {"i": jnp.arange(4, dtype=jnp.int32),
                 "h": jnp.full((8,), 1.5, jnp.bfloat16),
                 "f": jnp.ones((4,), jnp.float32)}
        hit = inj.inject_grads(grads, jnp.int32(2))
        np.testing.assert_array_equal(np.asarray(hit["i"]),
                                      np.asarray(grads["i"]))
        n_bad = sum(int(jnp.sum(~jnp.isfinite(l)))
                    for l in (hit["h"].astype(jnp.float32),
                              hit["f"]))
        assert n_bad == 1
        miss = inj.inject_grads(grads, jnp.int32(3))
        for k in grads:
            assert miss[k].dtype == grads[k].dtype
            _tree_equal(grads[k], miss[k])

    def test_zero_size_leaves_cannot_host_faults(self):
        """Grads with empty leaves (unused/optional params) must not crash
        the injector; the fault lands on a non-empty leaf instead."""
        inj = rz.FaultInjector(rz.FaultPlan(seed=3, nan_grad_steps=(5,)))
        grads = {"empty": jnp.zeros((0,)), "used": jnp.ones((4,))}
        out = inj.inject_grads(grads, jnp.int32(5))
        assert bool(jnp.any(~jnp.isfinite(out["used"])))
        # all-empty tree: injection is a structured no-op
        only_empty = {"e": jnp.zeros((0,))}
        _tree_equal(only_empty, inj.inject_grads(only_empty, jnp.int32(5)))

    def test_preemption_only_at_configured_step(self):
        inj = rz.FaultInjector(rz.FaultPlan(preempt_steps=(4,)))
        inj.check_preemption(3)
        with pytest.raises(rz.SimulatedPreemption) as ei:
            inj.check_preemption(4)
        assert ei.value.step == 4

    def test_corruption_offsets_deterministic(self, tmp_path):
        tree = {"w": jnp.arange(64, dtype=jnp.float32)}
        p1 = rz.save_checkpoint(str(tmp_path / "a"), 0, tree)
        p2 = rz.save_checkpoint(str(tmp_path / "b"), 0, tree)
        offs1 = rz.FaultInjector(rz.FaultPlan(seed=9)).corrupt_checkpoint(p1)
        offs2 = rz.FaultInjector(rz.FaultPlan(seed=9)).corrupt_checkpoint(p2)
        assert offs1 == offs2


# --------------------------------------------------------------------------
# anomaly-aware guarded stepping
# --------------------------------------------------------------------------

def _quadratic_problem():
    params = {"w": jnp.ones((4, 4), jnp.float32) * 0.5,
              "b": jnp.zeros((4,), jnp.float32)}

    def loss_fn(p, batch):
        pred = batch @ p["w"] + p["b"]
        return jnp.mean(pred ** 2)

    return params, loss_fn


class TestGuardedStep:
    def test_clean_step_applies_update(self):
        params, loss_fn = _quadratic_problem()
        opt, scaler = FusedAdam(lr=1e-2), LossScaler(init_scale=2.0**8)
        step = jax.jit(rz.make_guarded_step(loss_fn, opt, scaler))
        ostate, sstate = opt.init(params), scaler.init()
        gstate = rz.init_guard_state(scaler)
        batch = jnp.ones((2, 4))
        p2, _, s2, g2, m = step(params, ostate, sstate, gstate, batch)
        assert not bool(m["found_inf"])
        assert int(g2.consecutive_skips) == 0
        assert int(s2.unskipped) == 1
        assert not np.array_equal(np.asarray(p2["w"]), np.asarray(params["w"]))
        assert rz.nonfinite_report(m["nonfinite"]) == {}

    def test_overflow_skips_bit_identically(self):
        params, loss_fn = _quadratic_problem()
        opt, scaler = FusedAdam(lr=1e-2), LossScaler(init_scale=2.0**8)
        step = jax.jit(rz.make_guarded_step(loss_fn, opt, scaler))
        ostate, sstate = opt.init(params), scaler.init()
        gstate = rz.init_guard_state(scaler)
        bad = jnp.full((2, 4), jnp.inf)
        p2, o2, s2, g2, m = step(params, ostate, sstate, gstate, bad)
        assert bool(m["found_inf"])
        _tree_equal(params, p2)      # the capturable skip: params untouched
        _tree_equal(ostate, o2)      # ... and moments/step untouched
        assert int(s2.unskipped) == 0
        assert int(g2.consecutive_skips) == 1
        assert int(g2.total_skips) == 1

    def test_localization_names_offending_leaf(self):
        grads = {"clean": jnp.ones((4,)),
                 "dirty": jnp.asarray([1.0, jnp.nan, jnp.inf, 2.0])}
        report = rz.nonfinite_report(rz.nonfinite_counts(grads))
        assert list(report) == ["['dirty']"]
        assert report["['dirty']"] == 2

    def test_patience_trip_halves_floor_below_min_scale(self):
        """After ``patience`` consecutive skips the dynamic floor drops
        below the configured min_loss_scale — the degradation path that
        replaces an infinite skip loop."""
        scaler = LossScaler(init_scale=4.0, min_loss_scale=1.0)
        cfg = rz.GuardConfig(patience=2, min_floor=2.0**-4)
        sstate, gstate = scaler.init(), rz.init_guard_state(scaler)
        bad = jnp.ones((), jnp.bool_)
        floors, scales = [], []
        for _ in range(8):
            sstate, gstate = rz.guarded_update(
                scaler, sstate, gstate, bad, cfg)
            floors.append(float(gstate.scale_floor))
            scales.append(float(sstate.scale))
        assert floors[0] == 1.0          # first skip: floor untouched
        assert floors[1] == 0.5          # patience hit: floor halves
        assert min(floors) == 2.0**-4    # ... and clamps at min_floor
        assert min(scales) <= 2.0**-4    # scale actually followed it down
        assert min(scales) > 0.0

    def test_trip_step_backs_off_exactly_once(self):
        """With default hysteresis=1 the scaler already backs off on each
        overflow; the patience trip must not compound it into
        backoff_factor**2 per step (code-review finding)."""
        scaler = LossScaler(init_scale=2.0**16, min_loss_scale=1.0)
        cfg = rz.GuardConfig(patience=2, min_floor=2.0**-10)
        sstate, gstate = scaler.init(), rz.init_guard_state(scaler)
        bad = jnp.ones((), jnp.bool_)
        prev = float(sstate.scale)
        for _ in range(6):
            sstate, gstate = rz.guarded_update(
                scaler, sstate, gstate, bad, cfg)
            cur = float(sstate.scale)
            assert cur == prev * 0.5, (
                f"scale moved {prev} -> {cur}, expected exactly one halving")
            prev = cur

    def test_guard_config_rejects_degenerate_patience(self):
        """patience=0 would trip on clean steps and destroy loss scaling."""
        with pytest.raises(ValueError, match="patience"):
            rz.GuardConfig(patience=0)
        with pytest.raises(ValueError, match="floor_backoff"):
            rz.GuardConfig(floor_backoff=0.0)
        with pytest.raises(ValueError, match="min_floor"):
            rz.GuardConfig(min_floor=0.0)

    def test_static_scaler_scale_never_moves_under_guard(self):
        """dynamic=False means the scale is a constant; the guard's
        forced backoff must respect that (only counters/events remain)."""
        from apex_tpu.amp.scaler import static_loss_scaler

        scaler = static_loss_scaler(128.0)
        cfg = rz.GuardConfig(patience=2)
        sstate, gstate = scaler.init(), rz.init_guard_state(scaler)
        bad = jnp.ones((), jnp.bool_)
        for _ in range(6):
            sstate, gstate = rz.guarded_update(
                scaler, sstate, gstate, bad, cfg)
        assert float(sstate.scale) == 128.0
        assert int(gstate.total_skips) == 6  # accounting still works

    def test_clean_step_resets_consecutive_counter(self):
        scaler = LossScaler(init_scale=2.0**8)
        cfg = rz.GuardConfig(patience=3)
        sstate, gstate = scaler.init(), rz.init_guard_state(scaler)
        bad, ok = jnp.ones((), jnp.bool_), jnp.zeros((), jnp.bool_)
        sstate, gstate = rz.guarded_update(scaler, sstate, gstate, bad, cfg)
        sstate, gstate = rz.guarded_update(scaler, sstate, gstate, bad, cfg)
        assert int(gstate.consecutive_skips) == 2
        sstate, gstate = rz.guarded_update(scaler, sstate, gstate, ok, cfg)
        assert int(gstate.consecutive_skips) == 0
        assert int(gstate.total_skips) == 2
        assert float(gstate.scale_floor) == scaler.min_loss_scale

    def test_floor_event_emitted(self):
        records = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r.getMessage())
        logger = logging.getLogger("apex_tpu.events")
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        try:
            scaler = LossScaler(init_scale=4.0)
            cfg = rz.GuardConfig(patience=1)
            sstate, gstate = scaler.init(), rz.init_guard_state(scaler)
            sstate, gstate = rz.guarded_update(
                scaler, sstate, gstate, jnp.ones((), jnp.bool_), cfg)
            jax.effects_barrier()
        finally:
            logger.removeHandler(handler)
        events = [json.loads(r) for r in records]
        assert any(e["event"] == "loss_scale_floor_halved" for e in events)
        [ev] = [e for e in events if e["event"] == "loss_scale_floor_halved"]
        assert ev["consecutive_skips"] == 1

    def test_guard_state_checkpoints(self, tmp_path):
        scaler = LossScaler()
        gstate = rz.init_guard_state(scaler)._replace(
            total_skips=jnp.int32(7), scale_floor=jnp.float32(0.25))
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, gstate)
        restored, _ = mgr.restore(like=rz.init_guard_state(scaler))
        _tree_equal(gstate, restored)


# --------------------------------------------------------------------------
# structured events
# --------------------------------------------------------------------------

def test_emit_event_is_json_parseable():
    ev = emit_event("unit_test_event", answer=42, label="x")
    assert ev["event"] == "unit_test_event" and ev["answer"] == 42
    # and the logged line itself is a single JSON document
    line = json.dumps(ev, sort_keys=True, default=str)
    assert json.loads(line)["label"] == "x"


# --------------------------------------------------------------------------
# cross-microbatch skip consistency (pipeline layer)
# --------------------------------------------------------------------------

class TestMicrobatchSkipConsistency:
    def test_one_bad_microbatch_poisons_the_step(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_no_pipelining,
        )

        params = {"w": jnp.ones((4,), jnp.float32)}
        scaler = LossScaler(init_scale=2.0**4)
        sstate = scaler.init()

        def loss_fn(p, mb):
            return jnp.sum(p["w"] * mb)

        clean = jnp.ones((3, 4))
        loss, grads, found_inf = forward_backward_no_pipelining(
            loss_fn, params, clean, grad_scaler=scaler, scaler_state=sstate,
            with_found_inf=True)
        assert not bool(found_inf)

        dirty = clean.at[1, 2].set(jnp.inf)  # ONE bad microbatch of three
        loss, grads, found_inf = forward_backward_no_pipelining(
            loss_fn, params, dirty, grad_scaler=scaler, scaler_state=sstate,
            with_found_inf=True)
        assert bool(found_inf)
        # all-or-nothing: the whole accumulated update is skipped
        opt = FusedAdam(lr=1e-2)
        ostate = opt.init(params)
        unscaled, _ = scaler.unscale(grads, sstate)
        p2, o2 = opt.step(unscaled, params, ostate, found_inf=found_inf)
        _tree_equal(params, p2)
        _tree_equal(ostate, o2)

    def test_accumulated_flag_matches_per_microbatch_or(self):
        """Detection on summed grads == OR over per-microbatch checks
        (nonfinite is absorbing under IEEE addition) — the invariant the
        schedules rely on for consistent skip semantics."""
        from apex_tpu.transformer.pipeline_parallel import (
            accumulated_found_inf,
        )

        per_mb = [
            {"w": jnp.ones((4,))},
            {"w": jnp.asarray([1.0, jnp.inf, -jnp.inf, 0.0])},
            {"w": jnp.asarray([1.0, -jnp.inf, jnp.inf, 0.0])},  # cancels to nan
        ]
        summed = jax.tree.map(lambda *ls: sum(ls), *per_mb)
        assert bool(accumulated_found_inf(summed))
        assert not bool(accumulated_found_inf(
            jax.tree.map(lambda *ls: sum(ls), per_mb[0], per_mb[0])))


# --------------------------------------------------------------------------
# state round-trips: every NamedTuple/dataclass state in amp/ + optimizers/
# --------------------------------------------------------------------------

_OPTIMIZERS = [
    pytest.param(lambda: FusedAdam(lr=1e-2), id="FusedAdam"),
    pytest.param(lambda: FusedAdam(lr=1e-2, master_weights=True),
                 id="FusedAdam-masters"),
    pytest.param(lambda: FusedAdam(lr=1e-2, state_dtype=jnp.bfloat16),
                 id="FusedAdam-bf16-moments"),
    pytest.param(lambda: FusedLAMB(lr=1e-2), id="FusedLAMB"),
    pytest.param(lambda: FusedSGD(lr=1e-2, momentum=0.9), id="FusedSGD"),
    pytest.param(lambda: FusedNovoGrad(lr=1e-2), id="FusedNovoGrad"),
    pytest.param(lambda: FusedAdagrad(lr=1e-2), id="FusedAdagrad"),
    pytest.param(lambda: FusedMixedPrecisionLamb(lr=1e-2),
                 id="FusedMixedPrecisionLamb"),
]


class TestStateRoundTrip:
    @pytest.mark.parametrize("make_opt", _OPTIMIZERS)
    def test_optimizer_state_dict_roundtrip(self, make_opt):
        """init -> one real step (non-trivial moments) -> save -> restore
        into a fresh init: bit-identical, for every optimizer state type."""
        opt = make_opt()
        params = {"w": jnp.ones((4, 2), jnp.bfloat16),
                  "b": jnp.ones((2,), jnp.float32)}
        state = opt.init(params)
        grads = {"w": jnp.full((4, 2), 0.25, jnp.float32),
                 "b": jnp.full((2,), -0.5, jnp.float32)}
        _, state = opt.step(grads, params, state)
        d = opt.state_dict(state)
        assert all(isinstance(v, np.ndarray) for v in d.values())
        restored = opt.load_state_dict(d, like=opt.init(params))
        _tree_equal(state, restored)

    @pytest.mark.parametrize("make_opt", _OPTIMIZERS)
    def test_optimizer_state_checkpoint_roundtrip(self, make_opt, tmp_path):
        opt = make_opt()
        params = {"w": jnp.ones((3, 3), jnp.float32)}
        state = opt.init(params)
        _, state = opt.step({"w": jnp.full((3, 3), 0.1)}, params, state)
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, state)
        restored, _ = mgr.restore(like=opt.init(params))
        _tree_equal(state, restored)

    def test_scaler_state_roundtrip_including_unskipped(self, tmp_path):
        scaler = LossScaler(hysteresis=2)
        st = scaler.init()
        for flag in (False, False, True, False):
            st = scaler.update(st, jnp.bool_(flag))
        assert int(st.unskipped) == 3  # the checkpoint-parity counter moved
        # via state_dict (amp parity path)
        st2 = scaler.load_state_dict(scaler.state_dict(st))
        _tree_equal(st, st2)
        # via the validated checkpoint path
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, st)
        restored, _ = mgr.restore(like=scaler.init())
        _tree_equal(st, restored)

    def test_amp_state_dict_roundtrip(self):
        """amp.state_dict / amp.load_state_dict across every per-loss
        scaler state (AmpState dataclass plumbing)."""
        amped = amp.initialize(lambda p, x: x, {}, opt_level="O2",
                               num_losses=2)
        states = [amped.scaler.update(s, jnp.bool_(i == 0))
                  for i, s in enumerate(amped.scaler_states)]
        amped.scaler_states = states
        d = amp.state_dict(amped)
        amped2 = amp.initialize(lambda p, x: x, {}, opt_level="O2",
                                num_losses=2)
        amped2 = amp.load_state_dict(amped2, d)
        _tree_equal(states, amped2.scaler_states)


# --------------------------------------------------------------------------
# acceptance: kill mid-run, corrupt newest checkpoint, restart, resume
# --------------------------------------------------------------------------

N_STEPS = 12
PREEMPT_AT = 7


def _build():
    params = {"w": jnp.full((6, 6), 0.3, jnp.float32),
              "b": jnp.zeros((6,), jnp.float32)}
    opt = FusedAdam(lr=5e-2)
    scaler = LossScaler(init_scale=2.0**6, growth_interval=4)

    def loss_fn(p, batch):
        pred = jnp.tanh(batch @ p["w"]) + p["b"]
        return jnp.mean((pred - 1.0) ** 2)

    return params, opt, scaler, loss_fn


def _batch(rng_key, i):
    return jax.random.normal(jax.random.fold_in(rng_key, i), (4, 6))


def _train(ckpt_root, *, injector=None, keep=3):
    """Restart-safe training loop (the docs/index.md recipe shape).

    Returns (state, {step: loss}) for the steps THIS invocation ran.
    """
    params, opt, scaler, loss_fn = _build()
    step_fn = jax.jit(rz.make_guarded_step(loss_fn, opt, scaler))
    state = {"params": params, "opt": opt.init(params),
             "scaler": scaler.init(), "guard": rz.init_guard_state(scaler),
             "rng": jax.random.PRNGKey(0)}
    mgr = rz.CheckpointManager(str(ckpt_root), keep=keep)
    try:
        state, last = mgr.restore(like=state)
        start = last + 1
    except rz.CheckpointError:
        start = 0
    losses = {}
    for i in range(start, N_STEPS):
        if injector is not None:
            injector.check_preemption(i)
        out = step_fn(state["params"], state["opt"], state["scaler"],
                      state["guard"], _batch(state["rng"], i))
        state = dict(zip(("params", "opt", "scaler", "guard"), out[:4]),
                     rng=state["rng"])
        losses[i] = float(out[4]["loss"])
        mgr.save(i, state)
    return state, losses


def test_preempt_corrupt_restart_resumes_bit_identically(tmp_path):
    """THE acceptance run (ISSUE 1): a training loop is killed mid-run by
    an injected preemption, the newest on-disk checkpoint is corrupted,
    the run restarts, falls back to the last VALID checkpoint, and
    resumes with bit-identical params/optimizer/scaler state and a loss
    trajectory matching an uninterrupted run exactly."""
    # reference: uninterrupted
    ref_root = tmp_path / "ref"
    ref_state, ref_losses = _train(ref_root, keep=N_STEPS)
    assert sorted(ref_losses) == list(range(N_STEPS))

    # victim: killed at step PREEMPT_AT, newest checkpoint then corrupted
    victim_root = tmp_path / "victim"
    injector = rz.FaultInjector(rz.FaultPlan(seed=13,
                                             preempt_steps=(PREEMPT_AT,)))
    with pytest.raises(rz.SimulatedPreemption):
        _train(victim_root, injector=injector)
    mgr = rz.CheckpointManager(str(victim_root), keep=3)
    newest = mgr.all_steps()[-1]
    assert newest == PREEMPT_AT - 1
    injector.corrupt_checkpoint(mgr.checkpoint_path(newest))

    # restart: must fall back past the corrupt newest...
    assert mgr.latest_valid_step() == newest - 1

    # ...restore bit-identical state at that step (vs. the reference's
    # checkpoint of the same step)...
    params, opt, scaler, _ = _build()
    like = {"params": params, "opt": opt.init(params),
            "scaler": scaler.init(), "guard": rz.init_guard_state(scaler),
            "rng": jax.random.PRNGKey(0)}
    resumed_state, resumed_step = rz.restore_checkpoint(
        str(victim_root), like)
    assert resumed_step == newest - 1
    ref_at_step, _ = rz.restore_checkpoint(
        str(ref_root), like, step=resumed_step)
    _tree_equal(resumed_state, ref_at_step)

    # ...and finish the run on the reference trajectory, bit for bit.
    final_state, resumed_losses = _train(victim_root)
    assert sorted(resumed_losses) == list(range(resumed_step + 1, N_STEPS))
    for i, loss in resumed_losses.items():
        assert loss == ref_losses[i], (
            f"post-resume loss diverged at step {i}: {loss} != {ref_losses[i]}")
    _tree_equal(final_state["params"], ref_state["params"])
    _tree_equal(final_state["opt"], ref_state["opt"])
    _tree_equal(final_state["scaler"], ref_state["scaler"])
    _tree_equal(final_state["guard"], ref_state["guard"])


def test_injected_nan_step_skips_but_run_recovers(tmp_path):
    """A transient NaN-gradient fault must cost one skipped step (scale
    backs off) and leave the run converging — not poison the params."""
    params, opt, scaler, loss_fn = _build()
    injector = rz.FaultInjector(rz.FaultPlan(seed=3, nan_grad_steps=(2,)))
    scaler_state, gstate = scaler.init(), rz.init_guard_state(scaler)
    ostate = opt.init(params)
    rng = jax.random.PRNGKey(0)

    @jax.jit
    def step_fn(p, o, s, g, batch, i):
        def scaled(pp):
            loss = loss_fn(pp, batch)
            return scaler.scale_loss(loss, s), loss

        grads, loss = jax.grad(scaled, has_aux=True)(p)
        grads = injector.inject_grads(grads, i)  # fault inside jit
        grads, found_inf = scaler.unscale(grads, s)
        p2, o2 = opt.step(grads, p, o, found_inf=found_inf)
        s2, g2 = rz.guarded_update(scaler, s, g, found_inf)
        return p2, o2, s2, g2, loss, found_inf

    eval_batch = _batch(rng, 1000)  # held-out: same batch before and after
    loss_before = float(loss_fn(params, eval_batch))
    skipped, losses = [], []
    for i in range(6):
        params, ostate, scaler_state, gstate, loss, found_inf = step_fn(
            params, ostate, scaler_state, gstate, _batch(rng, i),
            jnp.int32(i))
        skipped.append(bool(found_inf))
        losses.append(float(loss))
    assert skipped == [False, False, True, False, False, False]
    assert int(gstate.total_skips) == 1
    assert all(np.isfinite(l) for l in losses)
    assert all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree.leaves(params))
    # still converging after the fault (same held-out batch, fewer nats)
    assert float(loss_fn(params, eval_batch)) < loss_before


# --------------------------------------------------------------------------
# asynchronous checkpoint pipeline (ISSUE 8): snapshot on the hot path,
# background writer, crash consistency, vetoable commit
# --------------------------------------------------------------------------


def _dir_bytes(path):
    return {name: open(os.path.join(path, name), "rb").read()
            for name in sorted(os.listdir(path))}


class TestAsyncCheckpoint:
    def test_async_v1_bytes_and_restore_identical_to_sync(self, tmp_path):
        state = _state_tree(3)
        sync_path = rz.save_checkpoint(str(tmp_path / "sync"), 7, state)
        ac = rz.AsyncCheckpointer(
            rz.CheckpointManager(str(tmp_path / "async")))
        fut = ac.save(7, state)
        assert fut.result() is not None and fut.done()
        assert fut.snapshot_s is not None and fut.write_s is not None
        # the on-disk format is BYTE-identical: async is scheduling, not
        # a format change
        assert _dir_bytes(sync_path) == _dir_bytes(fut.path)
        a, sa = rz.restore_checkpoint(str(tmp_path / "sync"),
                                      like=_state_tree())
        b, sb = rz.restore_checkpoint(str(tmp_path / "async"),
                                      like=_state_tree())
        assert sa == sb == 7
        _tree_equal(a, b)

    def test_async_v2_sharded_bytes_and_restore_identical(self, tmp_path):
        state = _state_tree(5)
        sync_path = rz.save_sharded_checkpoint(str(tmp_path / "sync"), 9,
                                               state)
        ac = rz.AsyncCheckpointer(
            rz.ShardedCheckpointManager(str(tmp_path / "async")))
        fut = ac.save(9, state)
        fut.result()
        assert _dir_bytes(sync_path) == _dir_bytes(fut.path)
        a, sa = rz.restore_sharded_checkpoint(str(tmp_path / "sync"),
                                              like=_state_tree())
        b, sb = rz.restore_sharded_checkpoint(str(tmp_path / "async"),
                                              like=_state_tree())
        assert sa == sb == 9
        _tree_equal(a, b)

    def test_snapshot_is_donation_safe(self, tmp_path):
        """Mutating the live state after save() returns must not change
        what the background writer serializes — the snapshot owns its
        bytes (on CPU, device_get can alias the live buffer)."""
        import threading

        live = {"w": np.arange(16.0, dtype=np.float32)}
        want = live["w"].copy()
        gate = threading.Event()
        ac = rz.AsyncCheckpointer(
            rz.CheckpointManager(str(tmp_path)),
            progress_hook=lambda p: gate.wait(10.0))
        fut = ac.save(0, live)
        live["w"] *= -1.0  # the "next step" clobbers the live buffer
        gate.set()
        fut.result()
        restored, _ = rz.restore_checkpoint(
            str(tmp_path), like={"w": np.zeros(16, np.float32)})
        np.testing.assert_array_equal(np.asarray(restored["w"]), want)

    def test_backpressure_blocks_next_save_until_write_drains(
            self, tmp_path):
        import threading

        from apex_tpu.resilience import async_checkpoint as ackpt

        tree = {"w": jnp.arange(8.0)}
        gate = threading.Event()
        gates = {0: gate}  # only step 0's write is held open

        def hook(progress):
            g = gates.get(progress["step"])
            if g is not None:
                assert g.wait(10.0)

        ac = rz.AsyncCheckpointer(rz.CheckpointManager(str(tmp_path)),
                                  progress_hook=hook)
        before = ackpt._BACKPRESSURE.value()
        fut0 = ac.save(0, tree)
        second = {}

        def submit():
            second["fut"] = ac.save(1, tree)

        t = threading.Thread(target=submit)
        t.start()
        t.join(timeout=0.2)
        # save(1) is blocked joining the in-flight write — the step
        # loop's thread, not the write, is what backpressure stalls
        assert t.is_alive() and not fut0.done()
        gate.set()
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert fut0.error is None
        second["fut"].result()
        assert ackpt._BACKPRESSURE.value() == before + 1
        assert rz.latest_valid_step(str(tmp_path)) == 1

    def test_crash_mid_write_never_commits_and_falls_back(self, tmp_path):
        """THE crash-consistency run (v1): kill the writer mid-write;
        no partially written dir is ever selectable, restore falls back
        to the previous step bit-identically, the litter is swept."""
        root = str(tmp_path)
        state0, state1 = _state_tree(0), _state_tree(1)
        mgr = rz.CheckpointManager(root, keep=3)
        mgr.save(0, state0)

        crash = rz.CrashCheckpointWriter(after_records=2)
        ac = rz.AsyncCheckpointer(mgr, progress_hook=crash)
        fut = ac.save(1, state1)
        fut.join()
        assert isinstance(fut.error, rz.SimulatedWriterCrash)
        assert crash.fired
        # hard-kill semantics: the partial temp dir is LEFT on disk...
        litter = [n for n in os.listdir(root) if n.startswith(_TMP_PREFIX)]
        assert litter
        # ...but can never be selected: not a step dir, never committed
        assert rz.latest_valid_step(root) is None or \
            rz.latest_valid_step(root) == 0
        assert rz.latest_valid_step(root) == 0
        restored, step = mgr.restore(like=_state_tree())
        assert step == 0
        _tree_equal(restored, state0)
        # the next save sweeps the orphaned litter and commits normally
        ac2 = rz.AsyncCheckpointer(mgr)
        ac2.save(2, state1).result()
        assert not [n for n in os.listdir(root)
                    if n.startswith(_TMP_PREFIX)]
        assert rz.latest_valid_step(root) == 2

    def test_crash_mid_write_sharded_falls_back(self, tmp_path):
        """Crash consistency on the v2 (sharded) format, and async-vs-
        sync restores stay bit-identical across the fallback."""
        root_a, root_s = str(tmp_path / "a"), str(tmp_path / "s")
        state0 = _state_tree(0)
        mgr = rz.ShardedCheckpointManager(root_a, keep=3)
        ac = rz.AsyncCheckpointer(mgr)
        ac.save(0, state0).result()
        rz.save_sharded_checkpoint(root_s, 0, state0)

        crash = rz.CrashCheckpointWriter(after_records=3)
        ac_crash = rz.AsyncCheckpointer(mgr, progress_hook=crash)
        fut = ac_crash.save(1, _state_tree(1))
        fut.join()
        assert isinstance(fut.error, rz.SimulatedWriterCrash)
        assert rz.latest_valid_step(root_a) == 0
        a, sa = mgr.restore(like=_state_tree())
        b, sb = rz.restore_sharded_checkpoint(root_s, like=_state_tree())
        assert sa == sb == 0
        _tree_equal(a, b)

    def test_veto_aborts_commit_without_a_step_dir(self, tmp_path):
        import threading

        tree = {"w": jnp.arange(4.0)}
        gate = threading.Event()
        ac = rz.AsyncCheckpointer(
            rz.CheckpointManager(str(tmp_path)),
            progress_hook=lambda p: gate.wait(10.0))
        records = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r.getMessage())
        ev_logger = logging.getLogger("apex_tpu.events")
        ev_logger.addHandler(handler)
        ev_logger.setLevel(logging.INFO)  # order-independent capture
        try:
            fut = ac.save(3, tree)
            assert ac.veto("consistency failed") is True
            gate.set()
            fut.join()
        finally:
            ev_logger.removeHandler(handler)
        assert isinstance(fut.error, rz.SaveVetoed)
        assert fut.path is None
        assert rz.latest_valid_step(str(tmp_path)) is None
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.startswith(_TMP_PREFIX)]  # veto cleans its temp
        vetoed = [json.loads(m) for m in records
                  if '"checkpoint_commit_vetoed"' in m]
        assert vetoed and vetoed[0]["step"] == 3
        # a veto is not a failure: the next save proceeds cleanly
        ac.save(4, tree).result()
        assert rz.latest_valid_step(str(tmp_path)) == 4
        assert ac.veto("nothing in flight") is False

    def test_unharvested_failure_surfaces_on_next_save(self, tmp_path):
        tree = {"w": jnp.arange(4.0)}
        mgr = rz.CheckpointManager(str(tmp_path))
        crash = rz.CrashCheckpointWriter(after_records=1)
        ac = rz.AsyncCheckpointer(mgr, progress_hook=crash)
        fut = ac.save(0, tree)
        fut.join()
        # the failure was never polled/waited: the next save raises it
        # exactly where a synchronous manager.save would have
        with pytest.raises(rz.SimulatedWriterCrash):
            ac.save(1, tree)
        # ...once surfaced, the pipeline is clean again (crash is one-shot)
        ac.save(2, tree).result()
        assert rz.latest_valid_step(str(tmp_path)) == 2

    def test_sweep_and_rotation_respect_live_writer(self, tmp_path):
        """A concurrent save into the same root (the emergency path)
        must neither sweep the background writer's temp dir nor rotate
        away the step it is producing."""
        import threading

        root = str(tmp_path)
        tree = {"w": jnp.arange(64.0)}
        gate = threading.Event()
        ac = rz.AsyncCheckpointer(
            rz.CheckpointManager(root, keep=3),
            progress_hook=lambda p: gate.wait(10.0))
        fut = ac.save(5, tree)
        # while the writer is mid-flight, a sync save lands in the root
        rz.save_checkpoint(root, 6, tree, keep=1)
        litter = [n for n in os.listdir(root) if n.startswith(_TMP_PREFIX)]
        assert litter, "sync save swept the live writer's temp dir"
        gate.set()
        fut.result()
        steps = sorted(rz.CheckpointManager(root).all_steps())
        assert steps == [5, 6]
        assert not [n for n in os.listdir(root)
                    if n.startswith(_TMP_PREFIX)]

    def test_wait_and_poll_lifecycle(self, tmp_path):
        tree = {"w": jnp.arange(4.0)}
        ac = rz.AsyncCheckpointer(rz.CheckpointManager(str(tmp_path)))
        assert ac.poll() is None and ac.wait() is None
        fut = ac.save(0, tree)
        got = ac.wait()
        assert got is fut and got.error is None
        assert ac.poll() is None  # already harvested
        fut2 = ac.save(1, tree)
        fut2.join()
        assert ac.poll() is fut2  # done -> harvested without blocking

    def test_manager_without_two_phase_surface_rejected(self):
        with pytest.raises(TypeError):
            rz.AsyncCheckpointer(object())

    def test_writer_crash_hook_validates_and_targets_steps(self):
        with pytest.raises(ValueError):
            rz.CrashCheckpointWriter(after_records=0)
        hook = rz.CrashCheckpointWriter(after_records=1, steps=(7,))
        hook({"step": 3, "record": 0, "bytes": 8})  # wrong step: no fire
        assert not hook.fired
        with pytest.raises(rz.SimulatedWriterCrash):
            hook({"step": 7, "record": 0, "bytes": 8})
        assert hook.fired
        hook({"step": 7, "record": 1, "bytes": 16})  # one-shot: no re-fire
