"""Elastic sharded checkpointing + cross-replica consistency (ISSUE 3).

Covers the tentpole end to end on the suite's 8-virtual-CPU-device mesh:
shard-grid geometry, sharded save/validate/restore with resharding onto
a *different* mesh shape, per-shard corruption localization + fallback,
cross-replica hash verification / desync localization / resync repair,
the supervisor's ``consistency_check_interval`` wiring, and THE
acceptance run — train on ``(dp=4, tp=2)``, inject ``DesyncReplica``
(detected, localized, resynced, trajectory bit-matches the clean run),
save sharded, restart on ``(dp=2, tp=4)`` and ``dp=8`` bit-identically,
and fall back past a ``CorruptShardFile``-damaged newest checkpoint.
"""

import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import resilience as rz
from apex_tpu.resilience.consistency import _SHARD_MAP_KW, _shard_map
from apex_tpu.resilience.elastic import _shard_grid, _spec_entries


@pytest.fixture
def events():
    """Capture structured apex_tpu.events as parsed dicts."""
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    logger = logging.getLogger("apex_tpu.events")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)

    def get(kind=None):
        parsed = [json.loads(r) for r in records]
        return parsed if kind is None else [e for e in parsed
                                            if e["event"] == kind]

    yield get
    logger.removeHandler(handler)


def _mesh(devices, dp, tp):
    return Mesh(np.array(devices[:8]).reshape(dp, tp), ("dp", "tp"))


@pytest.fixture
def mesh42(devices):
    return _mesh(devices, 4, 2)


@pytest.fixture
def mesh24(devices):
    return _mesh(devices, 2, 4)


@pytest.fixture
def mesh81(devices):
    return _mesh(devices, 8, 1)


def _host(leaf):
    from apex_tpu.utils.serialization import leaf_to_numpy

    return leaf_to_numpy(leaf)


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = _host(x), _host(y)
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y)


# --------------------------------------------------------------------------
# shard-grid geometry
# --------------------------------------------------------------------------


class TestShardGeometry:
    def test_spec_normalization(self):
        assert _spec_entries(None, 2) == [(), ()]
        assert _spec_entries(P("tp"), 2) == [("tp",), ()]
        assert _spec_entries(P(None, ("dp", "tp")), 2) == [(), ("dp", "tp")]

    def test_grid_covers_leaf_exactly(self):
        sizes = {"dp": 4, "tp": 2}
        grid = list(_shard_grid([("tp",), ()], (8, 3), sizes, "x"))
        assert len(grid) == 2  # only 'tp' partitions
        assert [g[1] for g in grid] == [[[0, 4], [0, 3]], [[4, 8], [0, 3]]]
        assert [g[0] for g in grid] == [{"tp": 0}, {"tp": 1}]

    def test_tuple_entry_splits_major_to_minor(self):
        sizes = {"dp": 2, "tp": 2}
        grid = list(_shard_grid([(("dp", "tp"))], (8,), sizes, "x"))
        # dp major, tp minor: (dp, tp) -> start = (dp*2 + tp) * 2
        assert [g[1][0] for g in grid] == [
            [0, 2], [2, 4], [4, 6], [6, 8]]

    def test_replicated_leaf_is_one_shard(self):
        grid = list(_shard_grid([(), ()], (4, 4), {"dp": 8}, "x"))
        assert grid == [({}, [[0, 4], [0, 4]])]

    def test_uneven_dim_raises(self):
        with pytest.raises(rz.CheckpointError, match="not divisible"):
            list(_shard_grid([("tp",)], (7,), {"tp": 2}, "x"))

    def test_unknown_axis_raises(self):
        with pytest.raises(rz.CheckpointError, match="not a mesh axis"):
            list(_shard_grid([("zz",)], (8,), {"tp": 2}, "x"))


# --------------------------------------------------------------------------
# sharded checkpoints: save / validate / restore / reshard
# --------------------------------------------------------------------------


def _sharded_tree(mesh):
    """Representative state: tp-sharded matrix, dp+tp 2-D sharded matrix,
    replicated vector, scalar, typed PRNG key."""
    return {
        "w": jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P(None, "tp"))),
        "m": jax.device_put(
            jnp.arange(32, dtype=jnp.bfloat16).reshape(8, 4),
            NamedSharding(mesh, P("dp", "tp"))),
        "b": jax.device_put(jnp.ones((6,), jnp.float32),
                            NamedSharding(mesh, P())),
        "step": jnp.int32(7),
        "rng": jax.random.key(3),
    }


class TestShardedCheckpoint:
    def test_roundtrip_same_mesh_bit_identical(self, tmp_path, mesh42):
        tree = _sharded_tree(mesh42)
        path = rz.save_sharded_checkpoint(str(tmp_path), 5, tree,
                                          mesh=mesh42)
        rz.validate_sharded_checkpoint(path)
        restored, step = rz.restore_sharded_checkpoint(
            str(tmp_path), _sharded_tree(mesh42))
        assert step == 5
        _tree_equal(tree, restored)

    @pytest.mark.parametrize("shape", [(2, 4), (8, 1)])
    def test_reshard_onto_different_mesh_bit_identical(
            self, tmp_path, devices, mesh42, shape):
        tree = _sharded_tree(mesh42)
        rz.save_sharded_checkpoint(str(tmp_path), 0, tree, mesh=mesh42)
        target = _mesh(devices, *shape)
        restored, _ = rz.restore_sharded_checkpoint(
            str(tmp_path), _sharded_tree(target))
        _tree_equal(tree, restored)
        # the restored leaves live on the TARGET mesh's shardings
        assert restored["w"].sharding.mesh.shape == dict(target.shape)

    def test_manifest_v2_schema(self, tmp_path, mesh42):
        path = rz.save_sharded_checkpoint(
            str(tmp_path), 0, _sharded_tree(mesh42), mesh=mesh42)
        with open(os.path.join(path, "manifest.json")) as f:
            man = json.load(f)
        assert man["format_version"] == 2 and man["sharded"] is True
        assert man["mesh"]["axes"] == {"dp": 4, "tp": 2}
        assert man["mesh"]["dp"] == 4 and man["mesh"]["tp"] == 2
        assert man["mesh"]["world"] == 8
        by_path = {r["path"]: r for r in man["leaves"]}
        w = by_path["['w']"]
        assert w["shape"] == [8, 8]          # GLOBAL shape
        assert len(w["shards"]) == 2         # tp=2 column blocks
        assert {tuple(s["coords"].items()) for s in w["shards"]} == {
            (("tp", 0),), (("tp", 1),)}
        m = by_path["['m']"]
        assert len(m["shards"]) == 8         # dp=4 x tp=2 grid
        for s in m["shards"]:
            assert "crc32" in s and "index" in s and "offset" in s
        # replicated leaves are one shard with empty coords
        assert len(by_path["['b']"]["shards"]) == 1
        assert by_path["['b']"]["shards"][0]["coords"] == {}

    def test_specs_override_without_shardings(self, tmp_path, mesh42):
        """Host arrays + an explicit specs pytree shard the same way a
        NamedSharding-carrying tree does."""
        tree = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4)}
        rz.save_sharded_checkpoint(
            str(tmp_path), 0, tree, mesh=mesh42,
            specs={"w": P(None, "tp")})
        restored, _ = rz.restore_sharded_checkpoint(
            str(tmp_path), {"w": jnp.zeros((4, 4), jnp.float32)})
        _tree_equal(tree, restored)

    def test_validate_rejects_v1_dir(self, tmp_path):
        rz.save_checkpoint(str(tmp_path), 0, {"x": jnp.ones(3)})
        v1 = os.path.join(str(tmp_path), sorted(os.listdir(tmp_path))[0])
        with pytest.raises(rz.CheckpointError, match="not a sharded"):
            rz.validate_sharded_checkpoint(v1)

    def test_validate_checkpoint_dispatches_to_shards(self, tmp_path,
                                                      mesh42):
        """checkpoint.validate_checkpoint (and therefore
        latest_valid_step / the supervisor's emergency validation) walks
        v2 dirs shard-by-shard."""
        path = rz.save_sharded_checkpoint(
            str(tmp_path), 3, _sharded_tree(mesh42), mesh=mesh42)
        rz.validate_checkpoint(path)          # v1 entry point, v2 dir
        assert rz.latest_valid_step(str(tmp_path)) == 3
        rz.CorruptShardFile(leaf="w", seed=0)(path)
        with pytest.raises(rz.CheckpointError, match="CRC mismatch"):
            rz.validate_checkpoint(path)
        assert rz.latest_valid_step(str(tmp_path)) is None

    def test_v1_loader_refuses_v2_dir(self, tmp_path, mesh42):
        rz.save_sharded_checkpoint(
            str(tmp_path), 0, _sharded_tree(mesh42), mesh=mesh42)
        with pytest.raises(rz.CheckpointError, match="sharded"):
            rz.restore_checkpoint(str(tmp_path), _sharded_tree(mesh42))

    def test_template_mismatches_name_keystr(self, tmp_path, mesh42):
        tree = {"w": jax.device_put(
            jnp.ones((4, 4), jnp.float32),
            NamedSharding(mesh42, P(None, "tp")))}
        rz.save_sharded_checkpoint(str(tmp_path), 0, tree, mesh=mesh42)
        with pytest.raises(rz.CheckpointError, match=r"\['w'\]"):
            rz.restore_sharded_checkpoint(
                str(tmp_path), {"w": jnp.ones((4, 2), jnp.float32)},
                step=0)
        with pytest.raises(rz.CheckpointError, match=r"\['w'\]"):
            rz.restore_sharded_checkpoint(
                str(tmp_path), {"w": jnp.ones((4, 4), jnp.bfloat16)},
                step=0)
        with pytest.raises(rz.CheckpointError, match=r"no leaf \"\['v'\]\""):
            rz.restore_sharded_checkpoint(
                str(tmp_path), {"v": jnp.ones((4, 4), jnp.float32)},
                step=0)
        with pytest.raises(rz.CheckpointError, match=r"no leaf \"\['x'\]\""):
            # template leaf the checkpoint lacks
            rz.restore_sharded_checkpoint(
                str(tmp_path), {"w": jnp.ones((4, 4), jnp.float32),
                                "x": jnp.ones(2)}, step=0)

    def test_superset_checkpoint_names_extra_leaf(self, tmp_path, mesh42):
        tree = {"w": jnp.ones((4, 4), jnp.float32),
                "legacy": jnp.ones((2,), jnp.float32)}
        rz.save_sharded_checkpoint(str(tmp_path), 0, tree, mesh=mesh42)
        with pytest.raises(rz.CheckpointError,
                           match=r"template does not.*\['legacy'\]"):
            rz.restore_sharded_checkpoint(
                str(tmp_path), {"w": jnp.ones((4, 4), jnp.float32)},
                step=0)

    def test_mixed_root_falls_back_across_formats(self, tmp_path, mesh42,
                                                  events):
        """A root mixing v1 and v2 dirs: the sharded restore walk loads
        whichever format the newest VALID candidate carries."""
        host = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4)}
        rz.save_checkpoint(str(tmp_path), 0, host)            # v1
        rz.save_sharded_checkpoint(str(tmp_path), 1, host,    # v2
                                   mesh=mesh42,
                                   specs={"w": P(None, "tp")})
        dmg = rz.CorruptShardFile(seed=2)(
            os.path.join(str(tmp_path), "step_0000000001"))
        assert dmg["leaf"] == "['w']"
        restored, step = rz.restore_sharded_checkpoint(
            str(tmp_path), {"w": jnp.zeros((4, 4), jnp.float32)})
        assert step == 0                                      # fell back to v1
        _tree_equal(host, restored)
        assert any(e["step"] == 1 for e in events("checkpoint_rejected"))

    def test_rotation_and_manager_surface(self, tmp_path, mesh42):
        mgr = rz.ShardedCheckpointManager(str(tmp_path), keep=2,
                                          mesh=mesh42)
        tree = {"w": jnp.ones((4, 4), jnp.float32)}
        for s in range(5):
            mgr.save(s, tree, specs={"w": P(None, "tp")})
        assert mgr.all_steps() == [3, 4]
        assert mgr.latest_valid_step() == 4
        restored, step = mgr.restore(
            like={"w": jnp.zeros((4, 4), jnp.float32)})
        assert step == 4
        _tree_equal(tree, restored)

    def test_overlapping_shard_indices_rejected(self, tmp_path, mesh42):
        """A damaged-but-parsable manifest whose shard indices overlap
        (per-shard CRCs still pass — they cover bytes, not index
        semantics) must be rejected, not reassembled around np.empty
        garbage."""
        tree = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4)}
        path = rz.save_sharded_checkpoint(str(tmp_path), 0, tree,
                                          mesh=mesh42,
                                          specs={"w": P(None, "tp")})
        mp = os.path.join(path, "manifest.json")
        with open(mp) as f:
            man = json.load(f)
        shards = man["leaves"][0]["shards"]
        # both shards claim the SAME column block: byte totals still
        # look complete, columns 2-3 would be uninitialized memory
        shards[1]["index"] = shards[0]["index"]
        with open(mp, "w") as f:
            json.dump(man, f)
        like = {"w": jnp.zeros((4, 4), jnp.float32)}
        with pytest.raises(rz.CheckpointError, match="duplicate shard"):
            rz.validate_sharded_checkpoint(path)
        with pytest.raises(rz.CheckpointError, match="duplicate shard"):
            rz.restore_sharded_checkpoint(str(tmp_path), like, step=0)
        # gap variant: a shifted, non-chaining interval
        shards[1]["index"] = [[0, 4], [1, 3]]
        with open(mp, "w") as f:
            json.dump(man, f)
        with pytest.raises(rz.CheckpointError, match="do not tile"):
            rz.restore_sharded_checkpoint(str(tmp_path), like, step=0)

    def test_damaged_shape_record_rejects_not_crashes(self, tmp_path,
                                                      mesh42):
        """A parsable manifest whose leaf 'shape' is not a list must come
        back as CheckpointError — latest_valid_step and the fallback
        walk only skip CheckpointError, so a raw TypeError would crash
        the recovery path itself."""
        tree = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4)}
        path = rz.save_sharded_checkpoint(str(tmp_path), 0, tree,
                                          mesh=mesh42,
                                          specs={"w": P(None, "tp")})
        mp = os.path.join(path, "manifest.json")
        with open(mp) as f:
            man = json.load(f)
        man["leaves"][0]["shape"] = 16  # int, not a list
        with open(mp, "w") as f:
            json.dump(man, f)
        with pytest.raises(rz.CheckpointError, match="unusable shape"):
            rz.validate_sharded_checkpoint(path)
        assert rz.latest_valid_step(str(tmp_path)) is None

    def test_duplicate_axis_spec_rejected_at_save(self, tmp_path, mesh42):
        """A spec that repeats a mesh axis would emit duplicate shard
        indices — an unrestorable checkpoint save must refuse to write."""
        tree = {"w": jnp.ones((8, 8), jnp.float32)}
        with pytest.raises(rz.CheckpointError, match="more than once"):
            rz.save_sharded_checkpoint(str(tmp_path), 0, tree,
                                       mesh=mesh42,
                                       specs={"w": P("tp", "tp")})
        assert not any(n.startswith("step_")
                       for n in os.listdir(tmp_path))

    def test_uneven_shard_dim_raises_at_save(self, tmp_path, mesh42):
        tree = {"w": jnp.ones((7, 4), jnp.float32)}
        with pytest.raises(rz.CheckpointError, match="not divisible"):
            rz.save_sharded_checkpoint(str(tmp_path), 0, tree,
                                       mesh=mesh42,
                                       specs={"w": P("dp", None)})


# --------------------------------------------------------------------------
# cross-replica consistency
# --------------------------------------------------------------------------


def _stacked_state(mesh, seed=0):
    """Per-replica stacked params: leading 'dp' replica axis, tp-sharded
    second matrix dim, plus a logically-shared (non-stacked) scalar."""
    dp = int(mesh.shape["dp"])
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
    logical = {
        "w": jax.device_put(w, NamedSharding(mesh, P(None, "tp"))),
        "b": jax.device_put(b, NamedSharding(mesh, P("tp"))),
    }
    state = rz.expand_replicas(logical, mesh)
    state["shared"] = jax.device_put(
        jnp.float32(1.5), NamedSharding(mesh, P()))
    return state


class TestConsistency:
    def test_clean_state_verifies_empty(self, mesh42):
        assert rz.verify_replicas(_stacked_state(mesh42),
                                  mesh=mesh42) == []

    def test_replica_hashes_shape_and_agreement(self, mesh42):
        rec = rz.replica_hashes(_stacked_state(mesh42), mesh=mesh42)
        assert set(rec) == {"['b']", "['w']"}
        for r in rec.values():
            assert r["hashes"].shape == (4,)
            assert len(set(int(h) for h in r["hashes"])) == 1
            np.testing.assert_array_equal(r["max_abs_delta"], 0.0)

    def test_desync_localized_to_leaf_and_rank(self, mesh42, events):
        state = _stacked_state(mesh42)
        bad = rz.DesyncReplica([2], rank=3, leaf="w", delta=0.25)(state, 2)
        report = rz.verify_replicas(bad, mesh=mesh42, step=2)
        assert len(report) == 1
        d = report[0]
        assert d.path == "['w']" and d.ranks == (3,)
        assert d.max_abs_delta == pytest.approx(0.25, rel=1e-5)
        [ev] = events("replica_desync")
        assert ev["leaf"] == "['w']" and ev["ranks"] == [3]
        assert ev["step"] == 2
        [fail] = events("replica_verify_failed")
        assert fail["diverged_leaves"] == ["['w']"]

    def test_desync_off_step_is_identity(self, mesh42):
        state = _stacked_state(mesh42)
        assert rz.DesyncReplica([5])(state, 4) is state

    def test_desync_without_candidate_raises(self, mesh42):
        with pytest.raises(ValueError, match="no stacked floating"):
            rz.DesyncReplica([0], leaf="nope")(
                _stacked_state(mesh42), 0)

    def test_resync_repairs_bit_identically(self, mesh42):
        state = _stacked_state(mesh42)
        bad = rz.DesyncReplica([0], rank=2, leaf="b")(state, 0)
        fixed = rz.resync_replicas(bad, mesh=mesh42)
        assert rz.verify_replicas(fixed, mesh=mesh42) == []
        _tree_equal(fixed, state)  # rank 0 was clean: full state restored

    def test_resync_passes_through_shared_leaves(self, mesh42):
        state = _stacked_state(mesh42)
        fixed = rz.resync_replicas(state, mesh=mesh42)
        assert float(fixed["shared"]) == 1.5

    def test_collapse_expand_roundtrip(self, mesh42):
        state = _stacked_state(mesh42)
        logical = rz.collapse_replicas(state)
        assert np.shape(logical["w"]) == (8, 8)  # replica axis dropped
        assert np.shape(logical["shared"]) == ()  # untouched
        back = rz.expand_replicas(
            {"w": logical["w"], "b": logical["b"]}, mesh42)
        _tree_equal(back["w"], state["w"])
        _tree_equal(back["b"], state["b"])

    def test_policy_repairs_and_counts(self, mesh42, events):
        cons = rz.ReplicaConsistency(mesh=mesh42)
        state = _stacked_state(mesh42)
        bad = rz.DesyncReplica([1], rank=1, leaf="w")(state, 1)
        out = cons.check(bad, step=1)
        assert cons.resyncs == 1
        assert rz.verify_replicas(out, mesh=mesh42) == []
        [ev] = events("replica_resync")
        assert ev["leaves"] == ["['w']"] and ev["root"] == 0

    def test_policy_raises_when_resync_disabled(self, mesh42):
        cons = rz.ReplicaConsistency(mesh=mesh42, resync=False)
        bad = rz.DesyncReplica([0], rank=1, leaf="w")(
            _stacked_state(mesh42), 0)
        with pytest.raises(rz.ReplicaDesyncError, match=r"\['w'\]") as e:
            cons.check(bad, step=9)
        assert e.value.step == 9
        assert e.value.report[0].ranks == (1,)
        assert e.value.transient is False  # retry layer must never retry

    def test_policy_clean_state_is_identity(self, mesh42):
        cons = rz.ReplicaConsistency(mesh=mesh42)
        state = _stacked_state(mesh42)
        assert cons.check(state, step=0) is state
        assert cons.resyncs == 0

    def test_rank0_fault_repaired_from_majority(self, mesh42, events):
        """A fault on rank 0 itself must NOT be broadcast to the healthy
        majority: the repair elects a majority-consistent root."""
        state = _stacked_state(mesh42)
        bad = rz.DesyncReplica([0], rank=0, leaf="w", delta=0.5)(state, 0)
        out = rz.ReplicaConsistency(mesh=mesh42).check(bad, step=0)
        assert rz.verify_replicas(out, mesh=mesh42) == []
        _tree_equal(out, state)  # the majority's copy won, not rank 0's
        [ev] = events("replica_resync")
        assert ev["root"] != 0

    def test_majority_root_tie_falls_back_to_default(self):
        split = rz.DivergedLeaf(path="['x']", ranks=(1,),
                                max_abs_delta=1.0, hashes=(7, 8))
        assert rz.majority_root([split], default=0) == 0
        clear = rz.DivergedLeaf(path="['y']", ranks=(1, 2, 3),
                                max_abs_delta=1.0, hashes=(5, 9, 9, 9))
        assert rz.majority_root([clear], default=0) == 1
        # the elected root must be majority-consistent for EVERY leaf
        assert rz.majority_root([clear, split], default=0) == 0

    def test_collapse_handles_tuple_form_lead_entry(self, mesh42):
        """P(('dp',), ...) is the same sharding as P('dp', ...): the
        collapse must agree with what verify/resync call stacked."""
        leaf = jax.device_put(
            jnp.ones((4, 8), jnp.float32),
            NamedSharding(mesh42, P(("dp",), "tp")))
        out = rz.collapse_replicas({"w": leaf})
        assert np.shape(out["w"]) == (8,)

    def test_verify_handles_non_word_aligned_shards(self, mesh42):
        """Local shard byte counts that are not a multiple of the hash's
        u32 word size (bf16 x 3 = 6 bytes) still verify and localize."""
        logical = {"v": jax.device_put(
            jnp.arange(3, dtype=jnp.bfloat16),
            NamedSharding(mesh42, P()))}
        state = rz.expand_replicas(logical, mesh42)
        assert rz.verify_replicas(state, mesh=mesh42) == []
        bad = rz.DesyncReplica([0], rank=3, leaf="v", delta=1.0)(state, 0)
        report = rz.verify_replicas(bad, mesh=mesh42)
        assert [d.ranks for d in report] == [(3,)]

    def test_desync_guarantees_byte_change_in_low_precision(self, mesh42):
        """delta=1e-3 on bfloat16 values of magnitude 256 rounds to a
        no-op; the injector must still produce a real divergence."""
        logical = {"w": jax.device_put(
            jnp.full((8, 8), 256.0, jnp.bfloat16),
            NamedSharding(mesh42, P(None, "tp")))}
        state = rz.expand_replicas(logical, mesh42)
        bad = rz.DesyncReplica([0], rank=1, leaf="w", delta=1e-3)(state, 0)
        report = rz.verify_replicas(bad, mesh=mesh42)
        assert [d.ranks for d in report] == [(1,)]


# --------------------------------------------------------------------------
# supervisor wiring
# --------------------------------------------------------------------------


class _AlwaysDesynced:
    """Stub consistency pass whose repair never converges."""

    def __init__(self):
        self.calls = []

    def check(self, state, *, step):
        self.calls.append(step)
        raise rz.ReplicaDesyncError(step, [])


class TestSupervisorConsistency:
    def test_interval_runs_check_and_repairs(self, tmp_path, mesh42):
        """The supervisor runs the consistency pass every K steps and
        carries the repaired state forward."""
        cons = rz.ReplicaConsistency(mesh=mesh42)
        fault = rz.DesyncReplica([3], rank=2, leaf="w")
        sup = rz.TrainingSupervisor(
            None, rz.SupervisorConfig(step_deadline_s=300.0,
                                      consistency_check_interval=2),
            consistency=cons)

        def step_fn(state, batch, step):
            return fault(state, step)  # desync lands AFTER step 3

        state = _stacked_state(mesh42)
        final, last = sup.run(step_fn, state, iter(range(6)), num_steps=6)
        assert last == 5
        assert cons.resyncs == 1  # detected at the step-3 interval check
        assert rz.verify_replicas(final, mesh=mesh42) == []
        _tree_equal(final, state)  # rank 0 clean -> repair is exact

    def test_unrepairable_desync_escalates(self, tmp_path, events):
        """An unrepairable desync counts as an unrecovered failure and
        escalates through emergency-checkpoint + TrainingAborted."""
        mgr = rz.CheckpointManager(str(tmp_path))
        stub = _AlwaysDesynced()
        sup = rz.TrainingSupervisor(
            mgr, rz.SupervisorConfig(step_deadline_s=300.0,
                                     max_consecutive_failures=2,
                                     consistency_check_interval=1),
            consistency=stub)
        state = {"x": jnp.float32(0)}
        with pytest.raises(rz.TrainingAborted):
            sup.run(lambda s, b, i: s, state, iter(range(9)), num_steps=9)
        assert stub.calls == [0, 1]
        fails = events("supervisor_failure")
        assert [f["failure"] for f in fails] == ["ReplicaDesyncError"] * 2
        [abort] = events("supervisor_abort")
        assert abort["checkpoint"] is not None
        rz.validate_checkpoint(abort["checkpoint"])

    def test_persist_transform_saves_logical_form(self, tmp_path, devices,
                                                  mesh42):
        """With persist_transform=collapse_replicas, every checkpoint the
        supervisor writes stores the mesh-shape-free logical copy — so
        an elastic restart on a DIFFERENT dp world size restores it."""
        root = str(tmp_path / "sup_elastic")
        mgr = rz.ShardedCheckpointManager(root, mesh=mesh42)
        sup = rz.TrainingSupervisor(
            mgr, rz.SupervisorConfig(step_deadline_s=300.0,
                                     consistency_check_interval=2),
            consistency=rz.ReplicaConsistency(mesh=mesh42),
            persist_transform=rz.collapse_replicas)
        state = _stacked_state(mesh42)
        final, last = sup.run(lambda s, b, i: s, state,
                              iter(range(2)), num_steps=2)
        with open(os.path.join(mgr.checkpoint_path(last),
                               "manifest.json")) as f:
            man = json.load(f)
        by_path = {r["path"]: r for r in man["leaves"]}
        assert by_path["['w']"]["shape"] == [8, 8]  # replica axis gone
        mesh81 = _mesh(devices, 8, 1)
        template = rz.collapse_replicas(_stacked_state(mesh81))
        restored, step = rz.ShardedCheckpointManager(
            root, mesh=mesh81).restore(like=template)
        assert step == last
        _tree_equal(restored, rz.collapse_replicas(final))

    def test_desync_below_threshold_skips_periodic_commit(self, tmp_path):
        """An unrepairable desync must never let the periodic commit
        persist the untrusted state — a bit-rotted tree is internally
        consistent, so it would pass CRC validation, become
        latest_valid_step, and survive the restart."""
        mgr = rz.CheckpointManager(str(tmp_path))
        stub = _AlwaysDesynced()
        sup = rz.TrainingSupervisor(
            mgr, rz.SupervisorConfig(step_deadline_s=300.0,
                                     max_consecutive_failures=5,
                                     checkpoint_every=1,
                                     consistency_check_interval=1),
            consistency=stub)
        sup.run(lambda s, b, i: s, {"x": jnp.float32(0)},
                iter(range(3)), num_steps=3)
        assert stub.calls == [0, 1, 2]
        assert rz.latest_valid_step(str(tmp_path)) is None
        assert os.listdir(tmp_path) == []

    def test_standing_desync_escalates_across_intervals(self, tmp_path):
        """With interval > 1, the successful steps BETWEEN failed checks
        must neither reset the failure counter (the desync would never
        escalate) nor re-earn commit trust (the periodic save would
        persist the still-diverged state)."""
        mgr = rz.CheckpointManager(str(tmp_path))
        stub = _AlwaysDesynced()
        sup = rz.TrainingSupervisor(
            mgr, rz.SupervisorConfig(step_deadline_s=300.0,
                                     max_consecutive_failures=2,
                                     checkpoint_every=1,
                                     consistency_check_interval=3),
            consistency=stub)
        with pytest.raises(rz.TrainingAborted):
            sup.run(lambda s, b, i: s, {"x": jnp.float32(0)},
                    iter(range(9)), num_steps=9)
        assert stub.calls == [2, 5]  # escalated at the SECOND failure
        steps = sorted(int(n[len("step_"):])
                       for n in os.listdir(tmp_path)
                       if n.startswith("step_"))
        # steps 0-1 committed while trusted; 2-4 skipped (standing
        # desync); 5 is the ladder's emergency checkpoint at abort
        assert steps == [0, 1, 5]

    def test_interval_zero_never_checks(self):
        stub = _AlwaysDesynced()
        sup = rz.TrainingSupervisor(
            None, rz.SupervisorConfig(step_deadline_s=300.0),
            consistency=stub)
        sup.run(lambda s, b, i: s, {"x": 0}, iter(range(3)), num_steps=3)
        assert stub.calls == []

    def test_negative_interval_rejected(self):
        with pytest.raises(ValueError, match="consistency_check_interval"):
            rz.SupervisorConfig(consistency_check_interval=-1)


# --------------------------------------------------------------------------
# THE acceptance run (ISSUE 3)
# --------------------------------------------------------------------------

_H, _B, _LR = 8, 4, 2.0 ** -6
_PSPECS = {"w": P("dp", None, "tp"), "b": P("dp", "tp")}


def _init_train_state(mesh):
    dp = int(mesh.shape["dp"])
    w = (jnp.arange(_H * _H, dtype=jnp.float32).reshape(_H, _H)
         % 5 - 2) / 8.0
    b = (jnp.arange(_H, dtype=jnp.float32) % 3 - 1) / 4.0
    return {"w": jax.device_put(jnp.broadcast_to(w, (dp, _H, _H)),
                                NamedSharding(mesh, _PSPECS["w"])),
            "b": jax.device_put(jnp.broadcast_to(b, (dp, _H)),
                                NamedSharding(mesh, _PSPECS["b"]))}


def _batch(i):
    rng = np.random.default_rng(100 + i)
    return jnp.asarray(rng.integers(-2, 3, size=(_B, _H)), jnp.float32)


def _make_step(mesh):
    """One dp x tp train step over the stacked per-replica state: every
    dp rank computes grads on the (shared) batch, all-reduces them over
    'dp' (exact for identical summands at power-of-2 dp), and applies a
    plain SGD update to ITS OWN stacked copy — the representation a
    replica fault can actually diverge."""

    def body(params, x):
        w, b = params["w"][0], params["b"][0]  # this replica's copy
        y = x @ w + b                          # (B, H/tp) local columns
        gy = 2.0 * y
        gw = x.T @ gy
        gb = gy.sum(0)
        dpn = jax.lax.psum(1, "dp")
        gw = jax.lax.psum(gw, "dp") / dpn      # the dp all-reduce
        gb = jax.lax.psum(gb, "dp") / dpn
        loss = jax.lax.psum(jnp.sum(y * y), ("dp", "tp")) / dpn
        return ({"w": (w - _LR * gw)[None], "b": (b - _LR * gb)[None]},
                loss)

    return jax.jit(_shard_map(body, mesh=mesh, in_specs=(_PSPECS, P()),
                              out_specs=(_PSPECS, P()), **_SHARD_MAP_KW))


def _train(mesh, n_steps, *, state=None, start=0, fault=None,
           consistency=None):
    step_fn = _make_step(mesh)
    if state is None:
        state = _init_train_state(mesh)
    losses = []
    for i in range(start, start + n_steps):
        state, loss = step_fn(state, _batch(i))
        losses.append(float(loss))
        if fault is not None:
            state = fault(state, i)
        if consistency is not None:
            state = consistency.check(state, step=i)
    return state, losses


N1, N2, DESYNC_AT = 5, 4, 2


def test_elastic_acceptance_run(tmp_path, devices, events):
    """THE acceptance run (ISSUE 3): desync -> localize -> resync ->
    trajectory matches clean; sharded save on (dp=4, tp=2) -> restart on
    (dp=2, tp=4) and dp=8 bit-identically; shard corruption -> fallback
    to the newest fully-valid checkpoint with a structured event."""
    mesh42 = _mesh(devices, 4, 2)

    # ---- clean reference on (dp=4, tp=2)
    clean_state, clean_losses = _train(mesh42, N1 + N2)

    # ---- faulted run: rank 1's w silently diverges after step DESYNC_AT;
    # the per-step consistency pass detects, localizes, and resyncs it
    cons = rz.ReplicaConsistency(mesh=mesh42)
    fault = rz.DesyncReplica([DESYNC_AT], rank=1, leaf="w", delta=0.5)
    state, losses = _train(mesh42, N1, fault=fault, consistency=cons)

    assert cons.resyncs == 1
    [desync] = events("replica_desync")
    assert desync["leaf"] == "['w']" and desync["ranks"] == [1]
    assert desync["step"] == DESYNC_AT
    assert desync["max_abs_delta"] == pytest.approx(0.5, rel=1e-5)
    # the repair is exact (rank 0 was clean), so the trajectory matches
    # the clean run bit for bit
    assert losses == clean_losses[:N1]
    assert rz.verify_replicas(state, mesh=mesh42) == []

    # ---- sharded save at step N1-1 on (dp=4, tp=2); the persisted form
    # is the mesh-shape-free logical copy
    root = str(tmp_path / "elastic")
    mgr = rz.ShardedCheckpointManager(root, keep=3, mesh=mesh42)
    mgr.save(N1 - 1, rz.collapse_replicas(state))

    # ---- restart on (dp=2, tp=4) AND dp=8: bit-identical restore,
    # then the run continues
    for dp, tp in ((2, 4), (8, 1)):
        mesh = _mesh(devices, dp, tp)
        template = rz.collapse_replicas(_init_train_state(mesh))
        logical, resume = rz.ShardedCheckpointManager(
            root, mesh=mesh).restore(like=template)
        assert resume == N1 - 1
        # bit-identical resume: the restored logical state equals the
        # saved one exactly, resharded onto the NEW mesh
        _tree_equal(logical, rz.collapse_replicas(state))
        assert logical["w"].sharding.mesh.shape == dict(mesh.shape)

        restacked = rz.expand_replicas(logical, mesh)
        assert rz.verify_replicas(restacked, mesh=mesh) == []
        final, resumed_losses = _train(mesh, N2, state=restacked,
                                       start=resume + 1)
        # the continued trajectory tracks the uninterrupted clean run
        # (identical math; XLA tiling differs across tp widths, so the
        # comparison is tight-tolerance, not bit-exact)
        np.testing.assert_allclose(resumed_losses, clean_losses[N1:],
                                   rtol=1e-5)
        np.testing.assert_allclose(
            _host(rz.collapse_replicas(final)["w"]),
            _host(rz.collapse_replicas(clean_state)["w"]),
            rtol=1e-5, atol=1e-8)

    # ---- corrupt ONE shard of the newest checkpoint: restore falls
    # back to the previous fully-valid step with a structured event
    mgr.save(N1, rz.collapse_replicas(clean_state))
    assert mgr.latest_valid_step() == N1
    dmg = rz.CorruptShardFile(leaf="w", seed=7)(mgr.checkpoint_path(N1))
    assert dmg["leaf"] == "['w']"
    assert mgr.latest_valid_step() == N1 - 1
    template = rz.collapse_replicas(_init_train_state(mesh42))
    logical, step = mgr.restore(like=template)
    assert step == N1 - 1
    _tree_equal(logical, rz.collapse_replicas(state))
    rejected = [e for e in events("checkpoint_rejected")
                if e["step"] == N1]
    assert rejected and "CRC mismatch" in rejected[0]["reason"]
