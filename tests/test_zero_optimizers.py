"""ZeRO-2 distributed optimizers: numeric parity with the non-distributed
fused optimizers on an 8-device CPU mesh, plus state-sharding memory
accounting (VERDICT round-1 item 3; reference
apex/contrib/optimizers/distributed_fused_adam.py, distributed_fused_lamb.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.contrib.optimizers._zero_base import _merge_bf16, _split_bf16
from apex_tpu.optimizers import FusedAdam, FusedLAMB

N_STEPS = 3


def make_params(rng, dtype=jnp.float32):
    return {
        "w": jnp.asarray(rng.normal(size=(17, 9)), dtype),
        "b": jnp.asarray(rng.normal(size=(9,)), dtype),
        "ln": {"scale": jnp.asarray(1.0 + 0.1 * rng.normal(size=(33,)), dtype)},
    }


def make_grads(rng, params):
    return jax.tree.map(
        lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params)


def run_distributed(opt, params, base_grads, mesh, **step_kw):
    """N_STEPS of opt on the dp mesh; rank r's local grad = base * (r+1),
    so the reduced (mean) gradient is base * mean(1..8) = base * 4.5."""

    def fn(params, base_grads):
        state = opt.init(params)
        rank = jax.lax.axis_index("dp")
        scale = (rank + 1).astype(jnp.float32)
        for _ in range(N_STEPS):
            grads = jax.tree.map(lambda g: g * scale, base_grads)
            params, state = opt.step(grads, params, state, **step_kw)
        return params

    with mesh:
        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            **NO_REP_CHECK))(params, base_grads)


def run_reference(opt, params, base_grads):
    """N_STEPS of the non-distributed optimizer on the mean gradient."""
    state = opt.init(params)
    grads = jax.tree.map(lambda g: g * 4.5, base_grads)
    for _ in range(N_STEPS):
        params, state = opt.step(grads, params, state)
    return params


def assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            rtol=rtol, atol=atol),
        a, b)


def test_split_merge_bf16_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(257,)) * 1e3, jnp.float32)
    hi, lo = _split_bf16(x)
    assert hi.dtype == jnp.bfloat16 and lo.dtype == jnp.uint16
    np.testing.assert_array_equal(np.asarray(_merge_bf16(hi, lo)), np.asarray(x))


@pytest.mark.parametrize("adam_w_mode", [True, False])
def test_distributed_adam_matches_fused_adam(mesh8, rng, adam_w_mode):
    params = make_params(rng)
    grads = make_grads(rng, params)
    kw = dict(lr=1e-2, weight_decay=0.02, adam_w_mode=adam_w_mode)
    got = run_distributed(DistributedFusedAdam(**kw), params, grads, mesh8)
    want = run_reference(FusedAdam(**kw), params, grads)
    assert_trees_close(got, want)


def test_distributed_lamb_matches_fused_lamb(mesh8, rng):
    params = make_params(rng)
    grads = make_grads(rng, params)
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    got = run_distributed(DistributedFusedLAMB(**kw), params, grads, mesh8)
    want = run_reference(FusedLAMB(**kw), params, grads)
    assert_trees_close(got, want)


def test_store_param_remainders_tracks_fp32_master(mesh8, rng):
    """bf16 params + uint16 remainders == an exact fp32 master trajectory
    (reference's store_param_remainders,
    distributed_fused_adam.py 'store_param_remainders')."""
    params32 = make_params(rng)
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params32)
    grads = make_grads(rng, params32)
    got = run_distributed(
        DistributedFusedAdam(lr=1e-2, store_param_remainders=True),
        params16, grads, mesh8)
    assert all(p.dtype == jnp.bfloat16 for p in jax.tree.leaves(got))
    # master-weight FusedAdam keeps the same exact fp32 master; model params
    # differ only by bf16 rounding mode (truncation vs RNE) => 1 ulp
    want = run_reference(
        FusedAdam(lr=1e-2, master_weights=True),
        params16, grads)
    assert_trees_close(got, want, rtol=1e-2, atol=1e-2)


def test_store_param_remainders_requires_bf16(mesh8, rng):
    params = make_params(rng)  # fp32
    grads = make_grads(rng, params)
    with pytest.raises(Exception, match="bf16"):
        run_distributed(
            DistributedFusedAdam(store_param_remainders=True),
            params, grads, mesh8)


def test_scaled_states_fp16(mesh8, rng):
    """with_scaled_states keeps fp16 state near fp32 parity via per-tensor
    scales (the FP8-LM trick, distributed_fused_adam.py with_scaled_states)."""
    params = make_params(rng)
    # tiny grads would underflow unscaled fp16 state (min normal ~6e-5)
    grads = jax.tree.map(lambda g: g * 1e-6, make_grads(rng, params))
    opt = DistributedFusedAdam(lr=1e-3, with_scaled_states=True)
    assert opt.state_dtype == jnp.float16
    got = run_distributed(opt, params, grads, mesh8)
    want = run_reference(FusedAdam(lr=1e-3), params, grads)
    assert_trees_close(got, want, rtol=2e-3, atol=1e-6)
    # and the state really was stored in fp16: unscaled fp16 state on these
    # gradients would flush the second moment (~1e-12²) to zero and the
    # update to garbage — parity above is the evidence the scales work


def test_found_inf_skips_update(mesh8, rng):
    params = make_params(rng)
    grads = make_grads(rng, params)
    opt = DistributedFusedAdam(lr=1e-2)

    def fn(params, grads):
        state = opt.init(params)
        new_params, new_state = opt.step(
            grads, params, state, found_inf=jnp.bool_(True))
        return new_params, new_state.step

    with mesh8:
        new_params, step = jax.jit(shard_map(
            fn, mesh=mesh8, in_specs=(P(), P()), out_specs=(P(), P()),
            **NO_REP_CHECK))(params, grads)
    # capturable semantics: the WHOLE state reverts on overflow, step
    # included, matching FusedOptimizer so bias corrections stay in lockstep
    assert int(step) == 0
    assert_trees_close(new_params, params, rtol=0, atol=0)


def test_state_is_sharded_over_dp(mesh8, rng):
    """Memory accounting: each device holds 1/8 of the flat state, vs the
    non-distributed optimizer's full replica (the point of ZeRO)."""
    params = make_params(rng)
    opt = DistributedFusedAdam(lr=1e-2, distributed_axis="dp")

    with mesh8:
        state = jax.jit(shard_map(
            opt.init, mesh=mesh8, in_specs=(P(),),
            out_specs=opt.state_specs(), **NO_REP_CHECK))(params)

    total = state.exp_avg.shape[0]
    n_elems = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert total % (1024 * 8) == 0 and total >= n_elems
    for arr in (state.exp_avg, state.exp_avg_sq, state.param_shard):
        shards = arr.addressable_shards
        assert len(shards) == 8
        assert all(s.data.shape == (total // 8,) for s in shards)


def test_grad_sync_dtype_bf16(mesh8, rng):
    params = make_params(rng)
    grads = make_grads(rng, params)
    got = run_distributed(
        DistributedFusedAdam(lr=1e-2, grad_sync_dtype=jnp.bfloat16),
        params, grads, mesh8)
    want = run_reference(FusedAdam(lr=1e-2), params, grads)
    assert_trees_close(got, want, rtol=2e-2, atol=2e-2)
