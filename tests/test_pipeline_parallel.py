"""Pipeline-parallel schedule tests on the 8-device CPU mesh.

Mirrors tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py: the
pipelined loss and grads must match a sequential single-device execution of
the same stacked stages, for both the plain and interleaved schedules.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    PipelineStageSpec,
    forward_backward_no_pipelining,
    forward_backward_pipelining_1f1b,
    forward_backward_pipelining_1f1b_interleaved,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)

HID = 8


@pytest.fixture
def pp4_mesh(devices):
    mesh = parallel_state.initialize_model_parallel(1, 4, devices=devices[:4])
    yield mesh
    parallel_state.destroy_model_parallel()


def _stage_fn(params, x):
    # one "layer": linear + gelu (wire format preserved)
    h = jnp.dot(x, params["w"], precision="highest") + params["b"]
    return jax.nn.gelu(h)


def _first_fn(params, mb):
    return mb["x"]  # identity embedding: wire = input


def _last_fn(params, y, mb):
    return jnp.mean((y - mb["y"]) ** 2)


SPEC = PipelineStageSpec(stage_fn=_stage_fn, first_fn=_first_fn, last_fn=_last_fn)


def _make_stage_params(rng, n_stages, key=0):
    return {
        "w": jnp.asarray(rng.standard_normal((n_stages, HID, HID)) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_stages, HID)) * 0.1, jnp.float32),
    }


def _sequential_reference(stacked, batches):
    """Run all stages sequentially per microbatch; mean loss + grads."""

    def loss(stacked):
        n_micro = batches["x"].shape[0]
        total = 0.0
        for m in range(n_micro):
            x = batches["x"][m]
            for s in range(stacked["w"].shape[0]):
                x = _stage_fn({"w": stacked["w"][s], "b": stacked["b"][s]}, x)
            total = total + jnp.mean((x - batches["y"][m]) ** 2)
        return total / n_micro

    return jax.value_and_grad(loss)(stacked)


# one n_micro per schedule family stays in tier-1; the other params are
# the same claim at another microbatch count and ride the slow tier
# (each is a multi-second XLA-CPU pipeline compile)
@pytest.mark.parametrize(
    "n_micro", [pytest.param(4, marks=pytest.mark.slow), 7])
def test_pipeline_matches_sequential(pp4_mesh, rng, n_micro):
    stacked = _make_stage_params(rng, 4)
    batches = {
        "x": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
        "y": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
    }
    ref_loss, ref_grads = _sequential_reference(stacked, batches)

    def run(stage_params, batches):
        # the leading stage dim [4, ...] shards to [1, ...] per rank
        p = jax.tree.map(lambda l: l[0], stage_params)
        loss, grads = forward_backward_pipelining_without_interleaving(
            SPEC, p, batches)
        return loss, jax.tree.map(lambda l: l[None], grads)

    loss, grads = shard_map(
        run, mesh=pp4_mesh,
        in_specs=({"w": P("pp"), "b": P("pp")}, P()),
        out_specs=(P(), {"w": P("pp"), "b": P("pp")}),
        **NO_REP_CHECK,
    )(stacked, batches)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["w"]), np.asarray(ref_grads["w"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["b"]), np.asarray(ref_grads["b"]),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize(
    "n_micro", [pytest.param(4, marks=pytest.mark.slow), 7])
def test_1f1b_matches_sequential(pp4_mesh, rng, n_micro):
    stacked = _make_stage_params(rng, 4)
    batches = {
        "x": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
        "y": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
    }
    ref_loss, ref_grads = _sequential_reference(stacked, batches)

    def run(stage_params, batches):
        p = jax.tree.map(lambda l: l[0], stage_params)
        loss, grads = forward_backward_pipelining_1f1b(SPEC, p, batches)
        return loss, jax.tree.map(lambda l: l[None], grads)

    loss, grads = jax.jit(shard_map(
        run, mesh=pp4_mesh,
        in_specs=({"w": P("pp"), "b": P("pp")}, P()),
        out_specs=(P(), {"w": P("pp"), "b": P("pp")}),
        **NO_REP_CHECK,
    ))(stacked, batches)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["w"]), np.asarray(ref_grads["w"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["b"]), np.asarray(ref_grads["b"]),
                               rtol=1e-4, atol=1e-6)


def test_1f1b_memory_flat_in_num_microbatches(pp4_mesh, rng):
    """The 1F1B memory contract: compiled temp memory must stay flat as
    num_microbatches grows (the two-sweep autodiff schedule grows O(n))."""

    def temp_bytes(schedule, n_micro):
        batches = {
            "x": jnp.zeros((n_micro, 2, HID), jnp.float32),
            "y": jnp.zeros((n_micro, 2, HID), jnp.float32),
        }
        stacked = _make_stage_params(rng, 4)

        def run(stage_params, batches):
            p = jax.tree.map(lambda l: l[0], stage_params)
            loss, grads = schedule(SPEC, p, batches)
            return loss, jax.tree.map(lambda l: l[None], grads)

        fn = jax.jit(shard_map(
            run, mesh=pp4_mesh,
            in_specs=({"w": P("pp"), "b": P("pp")}, P()),
            out_specs=(P(), {"w": P("pp"), "b": P("pp")}),
            **NO_REP_CHECK))
        mem = fn.lower(stacked, batches).compile().memory_analysis()
        assert mem is not None, "memory analysis unavailable on this backend"
        return mem.temp_size_in_bytes

    small = temp_bytes(forward_backward_pipelining_1f1b, 4)
    large = temp_bytes(forward_backward_pipelining_1f1b, 32)
    # 8x the microbatches must not cost anywhere near 8x the temps; allow
    # slack for XLA bookkeeping noise
    assert large <= small * 1.5 + 4096, (small, large)

    # and the bound is REAL: the autodiff two-sweep schedule's temps do
    # grow with n (this is the gap 1F1B exists to close)
    sweep_small = temp_bytes(
        forward_backward_pipelining_without_interleaving, 4)
    sweep_large = temp_bytes(
        forward_backward_pipelining_without_interleaving, 32)
    assert sweep_large > sweep_small * 2, (sweep_small, sweep_large)


def test_no_pipelining_matches_fullbatch(rng):
    params = {"w": jnp.asarray(rng.standard_normal((HID, HID)) * 0.3, jnp.float32)}
    batches = {
        "x": jnp.asarray(rng.standard_normal((4, 2, HID)), jnp.float32),
        "y": jnp.asarray(rng.standard_normal((4, 2, HID)), jnp.float32),
    }

    def loss_fn(p, mb):
        return jnp.mean((jnp.tanh(mb["x"] @ p["w"]) - mb["y"]) ** 2)

    loss, grads = forward_backward_no_pipelining(loss_fn, params, batches)
    # reference: mean over microbatches; grads summed over microbatches
    ref_losses = [loss_fn(params, jax.tree.map(lambda l: l[i], batches))
                  for i in range(4)]
    ref_grads = sum(
        np.asarray(jax.grad(loss_fn)(params, jax.tree.map(lambda l: l[i], batches))["w"])
        for i in range(4))
    np.testing.assert_allclose(float(loss), float(np.mean(ref_losses)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["w"]), ref_grads, rtol=1e-5, atol=1e-6)

    loss_fwd, g = forward_backward_no_pipelining(loss_fn, params, batches,
                                                 forward_only=True)
    assert g is None
    np.testing.assert_allclose(float(loss_fwd), float(loss), rtol=1e-6)


def test_get_forward_backward_func():
    assert get_forward_backward_func(None, 1) is forward_backward_no_pipelining
    # pp dispatches to the memory-bounded 1F1B schedules
    assert (get_forward_backward_func(None, 4)
            is forward_backward_pipelining_1f1b)
    assert (get_forward_backward_func(2, 4)
            is forward_backward_pipelining_1f1b_interleaved)


@pytest.mark.parametrize(
    "n_micro", [pytest.param(4, marks=pytest.mark.slow), 6])
def test_interleaved_matches_sequential(pp4_mesh, rng, n_micro):
    """vpp=2 over pp=4: 8 global stages; parity vs sequential 8-layer run."""
    vpp, pp = 2, 4
    stacked = _make_stage_params(rng, vpp * pp)  # [8, ...] global stage order
    batches = {
        "x": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
        "y": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
    }
    ref_loss, ref_grads = _sequential_reference(stacked, batches)

    # rank r holds chunks [r, r+pp] → per-rank leaves [vpp, ...]; global
    # stage v*pp + r maps to rank r chunk v, so reshape [vpp, pp, ...] and
    # shard the *second* dim over pp.
    per_rank = {
        "w": stacked["w"].reshape(vpp, pp, HID, HID),
        "b": stacked["b"].reshape(vpp, pp, HID),
    }

    def run(stage_params, batches):
        # inside: leaves [vpp, 1, ...] → squeeze the pp dim
        p = jax.tree.map(lambda l: l.squeeze(1), stage_params)
        loss, grads = forward_backward_pipelining_with_interleaving(
            SPEC, p, batches, num_model_chunks=vpp)
        return loss, jax.tree.map(lambda l: l[:, None], grads)

    loss, grads = shard_map(
        run, mesh=pp4_mesh,
        in_specs=({"w": P(None, "pp"), "b": P(None, "pp")}, P()),
        out_specs=(P(), {"w": P(None, "pp"), "b": P(None, "pp")}),
        **NO_REP_CHECK,
    )(per_rank, batches)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads["w"]).reshape(vpp * pp, HID, HID),
        np.asarray(ref_grads["w"]), rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize(
    "n_micro", [pytest.param(4, marks=pytest.mark.slow),
                pytest.param(6, marks=pytest.mark.slow), 7])
def test_1f1b_interleaved_matches_sequential(pp4_mesh, rng, n_micro):
    """Memory-bounded interleaved schedule: parity vs sequential AND vs the
    autodiff interleaved schedule (vpp=2 over pp=4, incl. a partial last
    microbatch group for n_micro=6/7)."""
    vpp, pp = 2, 4
    stacked = _make_stage_params(rng, vpp * pp)
    batches = {
        "x": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
        "y": jnp.asarray(rng.standard_normal((n_micro, 2, HID)), jnp.float32),
    }
    ref_loss, ref_grads = _sequential_reference(stacked, batches)

    per_rank = {
        "w": stacked["w"].reshape(vpp, pp, HID, HID),
        "b": stacked["b"].reshape(vpp, pp, HID),
    }

    def run(stage_params, batches):
        p = jax.tree.map(lambda l: l.squeeze(1), stage_params)
        loss, grads = forward_backward_pipelining_1f1b_interleaved(
            SPEC, p, batches, num_model_chunks=vpp)
        return loss, jax.tree.map(lambda l: l[:, None], grads)

    loss, grads = shard_map(
        run, mesh=pp4_mesh,
        in_specs=({"w": P(None, "pp"), "b": P(None, "pp")}, P()),
        out_specs=(P(), {"w": P(None, "pp"), "b": P(None, "pp")}),
        **NO_REP_CHECK,
    )(per_rank, batches)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads["w"]).reshape(vpp * pp, HID, HID),
        np.asarray(ref_grads["w"]), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grads["b"]).reshape(vpp * pp, HID),
        np.asarray(ref_grads["b"]), rtol=1e-4, atol=1e-6)


def test_1f1b_interleaved_memory_flat_in_num_microbatches(pp4_mesh, rng):
    """The interleaved-1F1B memory contract (VERDICT r2 item 3): compiled
    temp memory must stay flat as num_microbatches grows, where the
    autodiff interleaved schedule's grows O(n)."""
    vpp, pp = 2, 4

    def temp_bytes(schedule, n_micro):
        batches = {
            "x": jnp.zeros((n_micro, 2, HID), jnp.float32),
            "y": jnp.zeros((n_micro, 2, HID), jnp.float32),
        }
        stacked = _make_stage_params(rng, vpp * pp)
        per_rank = {
            "w": stacked["w"].reshape(vpp, pp, HID, HID),
            "b": stacked["b"].reshape(vpp, pp, HID),
        }

        def run(stage_params, batches):
            p = jax.tree.map(lambda l: l.squeeze(1), stage_params)
            loss, grads = schedule(SPEC, p, batches, num_model_chunks=vpp)
            return loss, jax.tree.map(lambda l: l[:, None], grads)

        fn = jax.jit(shard_map(
            run, mesh=pp4_mesh,
            in_specs=({"w": P(None, "pp"), "b": P(None, "pp")}, P()),
            out_specs=(P(), {"w": P(None, "pp"), "b": P(None, "pp")}),
            **NO_REP_CHECK))
        mem = fn.lower(per_rank, batches).compile().memory_analysis()
        assert mem is not None, "memory analysis unavailable on this backend"
        return mem.temp_size_in_bytes

    small = temp_bytes(forward_backward_pipelining_1f1b_interleaved, 4)
    large = temp_bytes(forward_backward_pipelining_1f1b_interleaved, 32)
    assert large <= small * 1.5 + 4096, (small, large)

    # the bound is real: the autodiff interleaved schedule's temps DO grow
    sweep_small = temp_bytes(forward_backward_pipelining_with_interleaving, 4)
    sweep_large = temp_bytes(forward_backward_pipelining_with_interleaving, 32)
    assert sweep_large > sweep_small * 2, (sweep_small, sweep_large)


def test_pipeline_forward_only(pp4_mesh, rng):
    stacked = _make_stage_params(rng, 4)
    batches = {
        "x": jnp.asarray(rng.standard_normal((3, 2, HID)), jnp.float32),
        "y": jnp.asarray(rng.standard_normal((3, 2, HID)), jnp.float32),
    }
    ref_loss, _ = _sequential_reference(stacked, batches)

    def run(stage_params, batches):
        p = jax.tree.map(lambda l: l[0], stage_params)
        loss, _ = forward_backward_pipelining_without_interleaving(
            SPEC, p, batches, forward_only=True)
        return loss

    loss = shard_map(
        run, mesh=pp4_mesh,
        in_specs=({"w": P("pp"), "b": P("pp")}, P()),
        out_specs=P(),
        **NO_REP_CHECK,
    )(stacked, batches)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
