"""Smoke: every public submodule imports (the reference's docker_extension_builds
import-failure grep, tests/docker_extension_builds/run.sh, as a unit test)."""

import importlib

import pytest

MODULES = [
    "apex_tpu",
    "apex_tpu.amp",
    "apex_tpu.fp16_utils",
    "apex_tpu.optimizers",
    "apex_tpu.multi_tensor_apply",
    "apex_tpu.utils",
    "apex_tpu.feature_registry",
]


@pytest.mark.parametrize("mod", MODULES)
def test_imports(mod):
    importlib.import_module(mod)


def test_feature_registry():
    from apex_tpu import feature_registry

    feats = feature_registry.available_features()
    assert "fused_optimizers" in feats
    assert "multi_tensor_apply" in feats
