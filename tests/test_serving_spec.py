"""Speculative decoding: exact-greedy n-gram drafting + batched
multi-token verification (ISSUE 9).

THE acceptance run: a repetitive long prompt + >= 40 greedy tokens
decoded with speculation enabled is **bit-identical** — exact f32
logits at every emitted position and the identical token stream — to
plain one-token decode, including across a mid-stream rejection +
rollback and with a concurrent neighbor slot mid-chunked-prefill (the
neighbor stays bit-isolated).  The mechanism: every verify row goes
through the same masked fixed-``max_len``-extent attention as a
single-token decode step, so "target argmax == drafted token" is an
exact accept test and a rejected row is rolled back (length commit)
before its garbage is ever readable.

Plus: the scheduler path (spec on == spec off, token for token, in
fewer steps), the non-greedy escape hatch (temperature>0 requests keep
the existing path byte-for-byte: same tokens, same event/metric
sequences, zero verify compiles), draft-bucket compile bounds, the
adaptive-k policy, EOS truncation inside an accepted draft, and the
prompt-lookup drafter itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs import bridge as obs_bridge
from apex_tpu.serving.draft import SpeculationConfig, adapt_k, propose

# GQA on purpose, like test_serving.py: kv_heads (2) < heads (4)
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def _rep_prompt(n=30, seed=3):
    """A repetitive prompt: an n-gram-matchable motif repeated."""
    rng = np.random.default_rng(seed)
    motif = [int(x) for x in rng.integers(0, CFG.vocab_size, 6)]
    return (motif * ((n + 5) // 6))[:n]


def _rand_prompt(n=8, seed=11):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, CFG.vocab_size, n)]


def _mk_engine(model, params, prefill_len=16, slots=2):
    return sv.DecodeEngine(model, params, slots=slots, max_len=MAX,
                           prefill_len=prefill_len)


@pytest.fixture(scope="module")
def eng_pair(model, params):
    """One warm (plain, spec) engine pair shared by every
    scheduler-level test below: slots free after each drain, streams
    are state-independent, and sharing keeps the file's compile bill
    at one program set instead of one per test.  Tests that assert
    *zero* verify compiles build their own fresh engines."""
    return _mk_engine(model, params), _mk_engine(model, params)


# ---------------------------------------------------------------------------
# THE acceptance run: spec decode == plain decode, bit for bit
# ---------------------------------------------------------------------------


def test_spec_decode_bit_identical_with_rejection_and_neighbor_prefill(
        model, params):
    """>= 40 greedy tokens via drafting + verification: identical token
    stream AND bit-identical f32 logits at every emitted position vs
    plain one-token decode — across a forced mid-stream rejection +
    rollback, with a neighbor slot chunk-prefilling concurrently, and
    with verify compiles bounded by the draft bucket table.  The
    neighbor's own prefill logits are asserted bit-isolated too."""
    prompt = _rep_prompt()
    n_steps = 44

    # -- plain reference: per-step logits + greedy stream
    eng_ref = _mk_engine(model, params)
    logits = eng_ref.prefill(0, list(prompt))
    stream = [int(jnp.argmax(logits))]
    plain_logits = []                  # plain_logits[i] follows stream[i]
    for _ in range(n_steps):
        l = eng_ref.decode(np.array([stream[-1], 0], np.int32),
                           np.array([True, False]))[0]
        plain_logits.append(np.asarray(l))
        stream.append(int(jnp.argmax(l)))

    # -- neighbor solo reference: chunked prefill of a long prompt on
    # an independent engine (eng_ref's other slot is free and its
    # programs are warm)
    long_prompt = _rand_prompt(n=40, seed=9)
    nref = np.asarray(eng_ref.prefill(1, long_prompt))

    # -- speculative run: drafts from prompt lookup; one draft is
    # deliberately corrupted to force a rejection + rollback mid-stream
    eng = _mk_engine(model, params)
    first = eng.prefill(0, list(prompt))
    assert int(jnp.argmax(first)) == stream[0]
    emitted = [stream[0]]
    checked = 0                        # emitted positions logits-checked
    n_verifies = 0
    forced_rejection = False
    neighbor_fed = 0
    neighbor_logits = None
    while len(emitted) - 1 < n_steps:
        # interleave: one 16-token chunk of the neighbor's prompt
        # between verifies (mid-chunked-prefill concurrency)
        if neighbor_fed < len(long_prompt):
            neighbor_logits = eng.prefill_chunk(
                1, long_prompt[neighbor_fed:neighbor_fed + 16])
            neighbor_fed += 16
        history = list(prompt) + emitted
        draft = propose(history, 4) or [emitted[-1]]   # any draft is exact
        if n_verifies == 5 and not forced_rejection:
            # corrupt the draft's first token: guaranteed rejection
            draft = [(stream[len(emitted)] + 1) % CFG.vocab_size] \
                + draft[1:]
            forced_rejection = True
        accepted, greedy, rows = eng.verify_draft(
            0, [emitted[-1]] + draft)
        n_verifies += 1
        rows = np.asarray(rows)
        step_tokens = list(draft[:accepted]) + [int(greedy[accepted])]
        for i, tok in enumerate(step_tokens):
            pos = len(emitted) - 1     # index into plain_logits
            if pos >= n_steps:
                break                  # past the recorded reference
            assert np.array_equal(rows[i], plain_logits[pos]), (
                f"spec logits diverged from plain decode at emitted "
                f"position {pos}")
            checked += 1
            emitted.append(tok)
    assert emitted == stream[:len(emitted)], "token stream diverged"
    assert len(emitted) - 1 >= 40 and checked >= 40
    assert forced_rejection, "the forced rejection never fired"
    # rejections happened and were survived (the forced one at least)
    assert eng.verify_compiles() <= len(eng.draft_buckets)
    assert eng.decode_compiles() == 0      # pure-verify decode phase
    # neighbor stayed bit-isolated through interleaved spec verifies
    assert neighbor_fed >= len(long_prompt)
    assert np.array_equal(np.asarray(neighbor_logits), nref), (
        "neighbor chunked prefill diverged next to speculative decode")


def test_verify_rejection_rolls_back_exactly(model, params):
    """A fully-rejected draft must leave the slot exactly one plain
    decode step ahead: same pending token, same length, and the next
    verify still produces bit-identical logits (the rolled-back rows
    are unreadable)."""
    prompt = _rand_prompt()
    eng_ref = _mk_engine(model, params)
    logits = eng_ref.prefill(0, list(prompt))
    stream = [int(jnp.argmax(logits))]
    plain = []
    for _ in range(4):
        l = eng_ref.decode(np.array([stream[-1], 0], np.int32),
                           np.array([True, False]))[0]
        plain.append(np.asarray(l))
        stream.append(int(jnp.argmax(l)))

    eng = _mk_engine(model, params)
    eng.prefill(0, list(prompt))
    wrong = [(stream[1] + 1) % CFG.vocab_size,
             (stream[2] + 1) % CFG.vocab_size]
    accepted, greedy, rows = eng.verify_draft(0, [stream[0]] + wrong)
    assert accepted == 0
    assert int(greedy[0]) == stream[1]          # the bonus IS the truth
    assert np.array_equal(np.asarray(rows)[0], plain[0])
    assert eng.lengths()[0] == len(prompt) + 1  # rolled back to +1
    # chain another verify after the rollback: still bit-exact
    accepted2, greedy2, rows2 = eng.verify_draft(
        0, [stream[1], stream[2], stream[3]])
    assert accepted2 == 2
    assert np.array_equal(np.asarray(rows2)[1], plain[2])
    assert [int(greedy2[i]) for i in (0, 1, 2)] == stream[2:5]


def test_verify_draft_guards(model, params):
    eng = _mk_engine(model, params)
    with pytest.raises(ValueError):        # never prefilled
        eng.verify_draft(0, [1, 2])
    eng.prefill(0, [1, 2, 3])
    with pytest.raises(ValueError):        # no draft to verify
        eng.verify_draft(0, [1])
    with pytest.raises(ValueError):        # past max_draft
        eng.verify_draft(0, [1] * (eng.max_draft + 2))
    with pytest.raises(ValueError):        # slot out of range
        eng.verify_draft(9, [1, 2])
    small = sv.DecodeEngine(model, params, slots=1, max_len=8,
                            prefill_len=8, draft_buckets=(1, 4))
    small.prefill(0, [1] * 6)
    with pytest.raises(ValueError):        # 6 + 4 real tokens > 8
        small.verify_draft(0, [1, 2, 3, 4])
    with pytest.raises(ValueError):        # buckets must fit the cache
        sv.DecodeEngine(model, params, slots=1, max_len=8,
                        prefill_len=8, draft_buckets=(8,))
    with pytest.raises(ValueError):        # not ascending
        sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                        prefill_len=8, draft_buckets=(4, 2))
    with pytest.raises(ValueError):        # 0-length draft bucket
        sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                        prefill_len=8, draft_buckets=(0, 2))
    assert sv.default_draft_buckets(8) == (1, 2, 4, 8)
    assert sv.default_draft_buckets(6) == (1, 2, 4, 6)
    assert sv.default_draft_buckets(1) == (1,)
    assert eng.draft_bucket_for(3) == 4
    with pytest.raises(ValueError):
        eng.draft_bucket_for(0)


# ---------------------------------------------------------------------------
# scheduler path: identical streams, fewer steps, adaptive drafting
# ---------------------------------------------------------------------------


def _run_sched(eng, *, speculation, requests):
    sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9,
                                           speculation=speculation)
    for r in requests:
        sched.submit(r)
    results = sched.run()
    return results, sched, eng


@pytest.mark.slow   # ~11 s: tier-1 keeps the engine-level verify parity
# (test_spec_decode_bit_identical_with_rejection_and_neighbor_prefill)
# plus the scheduler-driven spec streams in the eos / max_new_tokens /
# temperature-bypass tests below — this three-request rerun re-proves
# the same stream identity at larger token counts
def test_scheduler_spec_streams_identical_in_fewer_steps(eng_pair):
    reqs = lambda: [                                   # noqa: E731
        sv.Request("greedy_rep", _rep_prompt(), max_new_tokens=40),
        sv.Request("greedy_rand", _rand_prompt(), max_new_tokens=12),
        sv.Request("sampled", _rand_prompt(seed=5), max_new_tokens=8,
                   temperature=0.7, top_k=8, seed=13),
    ]
    plain, s_plain, e_plain = _run_sched(eng_pair[0], speculation=None,
                                         requests=reqs())
    spec, s_spec, e_spec = _run_sched(eng_pair[1],
                                      speculation=SpeculationConfig(),
                                      requests=reqs())
    for rid in ("greedy_rep", "greedy_rand", "sampled"):
        assert spec[rid].tokens == plain[rid].tokens, rid
        assert spec[rid].finish_reason == plain[rid].finish_reason
    # the repetitive stream accepted drafts, so the drain took fewer
    # shared steps than one-token-per-step decode
    assert s_spec.steps_run < s_plain.steps_run
    stats = s_spec.spec_stats
    assert stats["dispatches"] > 0
    assert stats["emitted"] >= stats["dispatches"]     # >= 1 token each
    assert stats["accepted"] <= stats["drafted"]
    assert e_spec.verify_compiles() <= len(e_spec.draft_buckets)
    assert e_spec.decode_compiles() == 1   # fall-back lanes still shared
    assert e_plain.verify_compiles() == 0


def test_eos_inside_accepted_draft_truncates_like_plain(eng_pair):
    """An EOS token emitted mid-verify must end the stream exactly
    where plain decode would have stopped — later accepted tokens are
    discarded, not emitted."""
    prompt = _rep_prompt()
    plain, _, _ = _run_sched(
        eng_pair[0], speculation=None,
        requests=[sv.Request("probe", prompt, max_new_tokens=40)])
    # pick an EOS that plain decode emits somewhere past the first token
    eos = plain["probe"].tokens[6]
    mk = lambda: [sv.Request("r", prompt, max_new_tokens=40,    # noqa: E731
                             eos_id=eos)]
    a, _, _ = _run_sched(eng_pair[0], speculation=None, requests=mk())
    b, sched_b, _ = _run_sched(eng_pair[1],
                               speculation=SpeculationConfig(),
                               requests=mk())
    assert a["r"].tokens == b["r"].tokens
    assert a["r"].finish_reason == b["r"].finish_reason == "eos"
    assert len(b["r"].tokens) <= 7


def test_spec_respects_max_new_tokens_exactly(eng_pair):
    for n in (1, 2, 5, 17):
        plain, _, _ = _run_sched(
            eng_pair[0], speculation=None,
            requests=[sv.Request(f"p{n}", _rep_prompt(),
                                 max_new_tokens=n)])
        spec, _, _ = _run_sched(
            eng_pair[1], speculation=SpeculationConfig(),
            requests=[sv.Request(f"s{n}", _rep_prompt(),
                                 max_new_tokens=n)])
        assert spec[f"s{n}"].tokens == plain[f"p{n}"].tokens
        assert len(spec[f"s{n}"].tokens) == n


# ---------------------------------------------------------------------------
# the non-greedy escape hatch: byte-for-byte bypass (ISSUE 9 satellite)
# ---------------------------------------------------------------------------

# wall-clock-derived event fields: the only payload allowed to differ
# between a speculation-enabled and -disabled run of a sampled request
_TIMING_FIELDS = ("ttft_s", "duration_s", "tokens_per_s", "per_token_ms",
                  "queue_wait_s", "time", "t_wall")


def _capture_run(model, params, speculation):
    events = []

    def sink(event):
        events.append({k: v for k, v in event.items()
                       if k not in _TIMING_FIELDS})

    spec_metrics_before = (
        obs_bridge.SERVING_SPEC_DRAFTED.value(),
        obs_bridge.SERVING_SPEC_ACCEPTED.value(),
        obs_bridge.SERVING_SPEC_REJECTED.value(),
    )
    _logging.add_event_sink(sink)
    try:
        # fresh engine on purpose: the bypass must leave it with ZERO
        # verify compiles, which a shared warm engine cannot witness
        results, sched, eng = _run_sched(
            _mk_engine(model, params), speculation=speculation,
            requests=[sv.Request("r", _rep_prompt(), max_new_tokens=12,
                                 temperature=0.9, top_k=8, seed=21),
                      sv.Request("s", _rand_prompt(), max_new_tokens=6,
                                 temperature=1.3, seed=4)])
    finally:
        _logging.remove_event_sink(sink)
    spec_metrics_delta = tuple(
        after - before for after, before in zip((
            obs_bridge.SERVING_SPEC_DRAFTED.value(),
            obs_bridge.SERVING_SPEC_ACCEPTED.value(),
            obs_bridge.SERVING_SPEC_REJECTED.value(),
        ), spec_metrics_before))
    return results, events, spec_metrics_delta, sched, eng


def test_temperature_requests_bypass_speculation_byte_for_byte(
        model, params):
    """Fixed-seed temperature>0 requests with speculation ENABLED must
    produce byte-identical token streams AND identical event/metric
    sequences as with speculation disabled: drafting silently bypassed,
    no verify compiles triggered, no speculation metrics touched."""
    off = _capture_run(model, params, None)
    on = _capture_run(model, params, SpeculationConfig())
    for rid in ("r", "s"):
        assert on[0][rid].tokens == off[0][rid].tokens
    # identical event sequences (kinds AND non-timing payloads)
    assert on[1] == off[1]
    assert not any(e.get("event") == "serving_spec_verify"
                   for e in on[1])
    # no speculation metric moved in either run
    assert on[2] == off[2] == (0.0, 0.0, 0.0)
    # no verify program was ever compiled, and the spec accounting
    # stayed untouched — the bypass is structural, not cosmetic
    assert on[4].verify_compiles() == 0
    assert on[3].spec_stats == {"dispatches": 0, "drafted": 0,
                                "accepted": 0, "emitted": 0}


def test_spec_verify_events_feed_metrics(eng_pair):
    """Greedy speculation emits serving_spec_verify events and the
    bridge turns them into the drafted/accepted/rejected counters, the
    acceptance-length histogram, and the speedup gauge."""
    drafted0 = obs_bridge.SERVING_SPEC_DRAFTED.value()
    accepted0 = obs_bridge.SERVING_SPEC_ACCEPTED.value()
    rejected0 = obs_bridge.SERVING_SPEC_REJECTED.value()
    hist0 = obs_bridge.SERVING_SPEC_ACCEPT_LENGTH.count()
    events = []
    _logging.add_event_sink(events.append)
    try:
        _, sched, eng = _run_sched(
            eng_pair[1], speculation=SpeculationConfig(),
            requests=[sv.Request("metrics_r", _rep_prompt(),
                                 max_new_tokens=24)])
    finally:
        _logging.remove_event_sink(events.append)
    stats = sched.spec_stats
    assert stats["dispatches"] > 0
    verifies = [e for e in events
                if e.get("event") == "serving_spec_verify"]
    assert len(verifies) == stats["dispatches"]
    for e in verifies:
        assert 0 <= e["accepted"] <= e["drafted"]
        assert e["bucket"] in eng.draft_buckets
        assert e["emitted"] >= 1
    assert (obs_bridge.SERVING_SPEC_DRAFTED.value() - drafted0
            == stats["drafted"])
    assert (obs_bridge.SERVING_SPEC_ACCEPTED.value() - accepted0
            == stats["accepted"])
    assert (obs_bridge.SERVING_SPEC_REJECTED.value() - rejected0
            == stats["drafted"] - stats["accepted"])
    assert (obs_bridge.SERVING_SPEC_ACCEPT_LENGTH.count() - hist0
            == stats["dispatches"])
    assert obs_bridge.SERVING_SPEC_SPEEDUP.value() == pytest.approx(
        stats["emitted"] / stats["dispatches"])


# ---------------------------------------------------------------------------
# the drafter and the adaptive-k policy (pure host logic)
# ---------------------------------------------------------------------------


def test_prompt_lookup_proposes_continuations():
    # longest suffix [2, 3] matched earlier -> continuation [4, 1, 2]
    assert propose([1, 2, 3, 4, 1, 2, 3], 3) == [4, 1, 2]
    # k caps the draft
    assert propose([1, 2, 3, 4, 1, 2, 3], 1) == [4]
    # a longer suffix match wins over a shorter one
    h = [7, 1, 2, 3, 9, 1, 2, 3, 5, 1, 2, 3]
    assert propose(h, 2)[:1] == [5]       # matches [1,2,3] at pos 5
    # most RECENT earlier occurrence wins within a suffix length
    assert propose([1, 2, 8, 1, 2, 9, 1, 2], 1) == [9]
    # ...but an occurrence too close to the end to carry a full draft
    # yields to an older one that can (the periodic-tail case)
    assert propose([9] * 10, 2) == [9, 9]
    assert propose([5, 6, 5, 6, 5, 6, 5, 6], 3) == [5, 6, 5]
    # a lone occurrence with a short continuation still drafts it
    assert propose([9, 9, 9, 9], 2) == [9]
    # no match -> empty (the fall-back signal)
    assert propose([1, 2, 3, 4, 5], 3) == []
    # degenerate inputs
    assert propose([], 3) == []
    assert propose([1], 3) == []
    assert propose([1, 2, 3], 0) == []


def test_adaptive_k_policy():
    cfg = SpeculationConfig(max_draft=8, min_draft=1)
    assert adapt_k(4, 4, 4, cfg) == 8      # full accept: double
    assert adapt_k(8, 8, 8, cfg) == 8      # capped at max
    assert adapt_k(2, 2, 2, cfg) == 4
    assert adapt_k(8, 8, 7, cfg) == 4      # any rejection: halve
    assert adapt_k(2, 2, 0, cfg) == 1
    assert adapt_k(1, 1, 0, cfg) == 1      # floored at min
    # a short (history-limited) draft fully accepted still grows
    assert adapt_k(4, 2, 2, cfg) == 8
    fixed = SpeculationConfig(max_draft=6, adaptive=False)
    assert adapt_k(3, 6, 0, fixed) == 6    # pinned
    with pytest.raises(ValueError):
        SpeculationConfig(max_draft=0)
    with pytest.raises(ValueError):
        SpeculationConfig(min_draft=4, max_draft=2)
    with pytest.raises(ValueError):
        SpeculationConfig(ngram_min=0)
    with pytest.raises(ValueError):
        SpeculationConfig(ngram_max=1, ngram_min=2)


def test_scheduler_rejects_overwide_speculation_config(model, params):
    eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=8, draft_buckets=(1, 2, 4))
    with pytest.raises(ValueError):
        sv.ContinuousBatchingScheduler(
            eng, speculation=SpeculationConfig(max_draft=8))
    # a config the table covers is fine
    sv.ContinuousBatchingScheduler(
        eng, speculation=SpeculationConfig(max_draft=4))
