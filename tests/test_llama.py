"""Llama model family: torch-oracle parity + tp sharding.

The logits of :class:`apex_tpu.models.LlamaForCausalLM` must match
``transformers.LlamaForCausalLM`` (torch CPU) with identical weights —
RMSNorm, rotary convention, GQA broadcast, SwiGLU, and the head all have
to line up exactly for this to pass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK as _NO_REP_CHECK
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models import LlamaConfig, LlamaForCausalLM

CFG = LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=176,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=64, rope_theta=10000.0)


def _hf_model_and_weights(cfg: LlamaConfig, seed=0):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFModel

    torch.manual_seed(seed)
    hf_cfg = HFConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.kv_heads,
        max_position_embeddings=cfg.max_position_embeddings,
        rope_theta=cfg.rope_theta, rms_norm_eps=cfg.rms_norm_eps,
        attention_bias=False, tie_word_embeddings=False)
    model = HFModel(hf_cfg).eval()
    return model


def _port_weights(hf, cfg: LlamaConfig):
    """HF state dict -> apex_tpu param pytree (transpose [out,in]->[in,out])."""
    sd = {k: np.asarray(v.detach().numpy()) for k, v in hf.state_dict().items()}

    def lin(name):
        return {"kernel": jnp.asarray(sd[name].T)}

    params = {
        "embed_tokens": {"embedding": jnp.asarray(
            sd["model.embed_tokens.weight"])},
        "norm": {"scale": jnp.asarray(sd["model.norm.weight"])},
        "lm_head": jnp.asarray(sd["lm_head.weight"]),
    }
    for i in range(cfg.num_hidden_layers):
        pre = f"model.layers.{i}."
        params[f"layers_{i}"] = {
            "input_layernorm": {"scale": jnp.asarray(
                sd[pre + "input_layernorm.weight"])},
            "post_attention_layernorm": {"scale": jnp.asarray(
                sd[pre + "post_attention_layernorm.weight"])},
            "self_attn": {
                "q_proj": lin(pre + "self_attn.q_proj.weight"),
                "k_proj": lin(pre + "self_attn.k_proj.weight"),
                "v_proj": lin(pre + "self_attn.v_proj.weight"),
                "o_proj": lin(pre + "self_attn.o_proj.weight"),
            },
            "mlp": {
                "gate_proj": lin(pre + "mlp.gate_proj.weight"),
                "up_proj": lin(pre + "mlp.up_proj.weight"),
                "down_proj": lin(pre + "mlp.down_proj.weight"),
            },
        }
    return {"params": params}


def test_logits_match_torch_oracle(rng):
    torch = pytest.importorskip("torch")
    hf = _hf_model_and_weights(CFG)
    params = _port_weights(hf, CFG)

    ids = rng.integers(0, CFG.vocab_size, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()      # [b, s, v]

    model = LlamaForCausalLM(CFG)
    logits = model.apply(params, jnp.asarray(ids, jnp.int32))  # [s, b, v]
    got = np.asarray(logits).transpose(1, 0, 2)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_loss_matches_logits_ce(rng):
    model = LlamaForCausalLM(CFG)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), ids)
    loss = model.apply(params, ids, labels=labels)
    assert loss.shape == (2, 16)
    logits = np.asarray(model.apply(params, ids)).transpose(1, 0, 2)
    m = logits.max(-1)
    lse = m + np.log(np.exp(logits - m[..., None]).sum(-1))
    tgt = np.take_along_axis(logits, np.asarray(labels)[..., None], -1)[..., 0]
    np.testing.assert_allclose(np.asarray(loss), lse - tgt, rtol=1e-4,
                               atol=1e-4)


def test_tied_embeddings_parity_and_grads(rng):
    """tie_word_embeddings=True (r3 advisor finding: untested branch).

    (a) logits equal an untied model whose lm_head was set to the
    embedding table; (b) the embedding gradient is the SUM of the untied
    model's embedding and head gradients — proving gradient flows through
    BOTH uses of the shared table (the self.variables head read is not a
    stop_gradient)."""
    import dataclasses

    tied_cfg = dataclasses.replace(CFG, tie_word_embeddings=True)
    tied = LlamaForCausalLM(tied_cfg)
    untied = LlamaForCausalLM(CFG)

    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    tied_params = tied.init(jax.random.PRNGKey(0), ids)
    assert "lm_head" not in tied_params["params"], "tied model grew a head"

    untied_params = jax.tree.map(lambda x: x, untied.init(
        jax.random.PRNGKey(0), ids))
    # same weights everywhere; head := embedding table
    emb = tied_params["params"]["embed_tokens"]["embedding"]
    for key in tied_params["params"]:
        untied_params["params"][key] = tied_params["params"][key]
    untied_params["params"]["lm_head"] = emb

    out_tied = tied.apply(tied_params, ids)
    out_untied = untied.apply(untied_params, ids)
    np.testing.assert_allclose(np.asarray(out_tied), np.asarray(out_untied),
                               rtol=1e-5, atol=1e-5)

    g_tied = jax.grad(lambda p: tied.apply(p, ids, labels=labels).mean())(
        tied_params)
    g_untied = jax.grad(
        lambda p: untied.apply(p, ids, labels=labels).mean())(untied_params)
    g_emb_tied = np.asarray(g_tied["params"]["embed_tokens"]["embedding"])
    g_sum = (np.asarray(g_untied["params"]["embed_tokens"]["embedding"])
             + np.asarray(g_untied["params"]["lm_head"]))
    assert np.abs(g_emb_tied).max() > 0, "no gradient reached the embedding"
    np.testing.assert_allclose(g_emb_tied, g_sum, rtol=1e-4, atol=1e-6)


def test_gqa_heads_shape():
    """kv_heads < heads runs the broadcast path and matches an MHA model
    in which the kv heads are explicitly repeated."""
    cfg = CFG
    model = LlamaForCausalLM(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), ids)
    hd = cfg.hidden_size // cfg.num_attention_heads
    k_kernel = params["params"]["layers_0"]["self_attn"]["k_proj"]["kernel"]
    assert k_kernel.shape == (cfg.hidden_size, cfg.kv_heads * hd)

    # MHA equivalent: duplicate each kv head group
    mha_cfg = LlamaConfig(**{**dataclasses_asdict(cfg),
                             "num_key_value_heads": cfg.num_attention_heads})
    rep = cfg.num_attention_heads // cfg.kv_heads

    def widen(kern):
        # [H, nkv*hd] -> [H, nq*hd] repeating each head block
        H = kern.shape[0]
        k3 = kern.reshape(H, cfg.kv_heads, hd)
        return jnp.repeat(k3, rep, axis=1).reshape(H, -1)

    mha_params = jax.tree.map(lambda x: x, params)
    for i in range(cfg.num_hidden_layers):
        attn = mha_params["params"][f"layers_{i}"]["self_attn"]
        attn["k_proj"] = {"kernel": widen(attn["k_proj"]["kernel"])}
        attn["v_proj"] = {"kernel": widen(attn["v_proj"]["kernel"])}
    mha_model = LlamaForCausalLM(mha_cfg)
    out_gqa = model.apply(params, ids)
    out_mha = mha_model.apply(mha_params, ids)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha),
                               rtol=1e-5, atol=1e-5)


def dataclasses_asdict(cfg):
    import dataclasses

    return dataclasses.asdict(cfg)


def test_tensor_parallel_matches_single(devices, rng):
    """tp=2 sharded logits == unsharded logits."""
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(2, 1,
                                                    devices=devices[:2])
    try:
        model = LlamaForCausalLM(CFG)
        ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), ids)
        ref = model.apply(params, ids)  # [s, b, v]

        hd = CFG.hidden_size // CFG.num_attention_heads

        def shard(path, leaf):
            name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
            if "embed_tokens" in name or name.endswith("lm_head"):
                return P("tp", None)       # vocab dim sharded
            if any(k in name for k in ("q_proj", "k_proj", "v_proj",
                                       "gate_proj", "up_proj")):
                return P(None, "tp")       # column parallel
            if any(k in name for k in ("o_proj", "down_proj")):
                return P("tp", None)       # row parallel
            return P()                     # norms replicated

        specs = jax.tree_util.tree_map_with_path(shard, params)

        def run(p, ids):
            out = model.apply(p, ids)
            from apex_tpu.transformer.tensor_parallel import (
                gather_from_tensor_model_parallel_region,
            )

            return gather_from_tensor_model_parallel_region(out, "tp")

        with mesh:
            out = jax.jit(shard_map(
                run, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
                **_NO_REP_CHECK))(params, ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        parallel_state.destroy_model_parallel()
