"""contrib loss kernels: xentropy, focal loss, transducer joint/loss.

Oracles mirror the reference test suites:
- xentropy: label_smoothing_raw from contrib/test/xentropy/test_label_smoothing.py
- focal: torchvision.ops.sigmoid_focal_loss formula (the ref test oracle)
- transducer: the per-batch python DP of contrib/transducer/_transducer_ref.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


# ---------------------------------------------------------------------------
# xentropy
# ---------------------------------------------------------------------------

def xent_oracle(x, target, padding_idx, smoothing):
    x = np.asarray(x, np.float64)
    m = x.max(-1, keepdims=True)
    logprobs = x - m - np.log(np.exp(x - m).sum(-1, keepdims=True))
    nll = -np.take_along_axis(logprobs, target[:, None], axis=-1)[:, 0]
    smooth = -logprobs.mean(-1)
    loss = (1 - smoothing) * nll + smoothing * smooth
    loss[target == padding_idx] = 0.0
    return loss


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_forward(smoothing):
    from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss

    rng = np.random.default_rng(0)
    N, V, pad = 64, 317, 0
    x = rng.standard_normal((N, V)).astype(np.float32) * 2
    t = rng.integers(0, V, N)
    t[rng.choice(N, N // 6, replace=False)] = pad

    got = SoftmaxCrossEntropyLoss.apply(jnp.asarray(x), jnp.asarray(t),
                                        smoothing, pad)
    np.testing.assert_allclose(got, xent_oracle(x, t, pad, smoothing),
                               rtol=1e-5, atol=1e-5)


def test_xentropy_grad_matches_autodiff_reference():
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    rng = np.random.default_rng(1)
    N, V, pad, s = 32, 129, 0, 0.1
    x = jnp.asarray(rng.standard_normal((N, V)), jnp.float32)
    t = jnp.asarray(rng.integers(0, V, N), jnp.int32)

    def ours(x):
        return softmax_cross_entropy_loss(x, t, s, pad).sum()

    def ref(x):
        lp = jax.nn.log_softmax(x, axis=-1)
        nll = -jnp.take_along_axis(lp, t[:, None], axis=-1)[:, 0]
        loss = (1 - s) * nll - s * lp.mean(-1)
        return jnp.where(t == pad, 0.0, loss).sum()

    np.testing.assert_allclose(jax.grad(ours)(x), jax.grad(ref)(x),
                               rtol=1e-4, atol=1e-5)


def test_xentropy_half_inputs_fp32_loss():
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((16, 64)), jnp.bfloat16)
    t = jnp.asarray(rng.integers(1, 64, 16), jnp.int32)
    loss = softmax_cross_entropy_loss(x, t, 0.1, 0)
    assert loss.dtype == jnp.float32
    g = jax.grad(lambda x: softmax_cross_entropy_loss(x, t, 0.1, 0).sum())(x)
    assert g.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# focal loss
# ---------------------------------------------------------------------------

def sigmoid_focal_oracle(x, y, alpha, gamma):
    """torchvision.ops.sigmoid_focal_loss with reduction='sum' (numpy)."""
    x = np.asarray(x, np.float64)
    p = 1 / (1 + np.exp(-x))
    ce = -(y * np.log(p) + (1 - y) * np.log1p(-p))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    return (a_t * (1 - p_t) ** gamma * ce).sum()


def test_focal_loss_matches_torchvision_formula():
    from apex_tpu.contrib.focal_loss import FocalLoss

    rng = np.random.default_rng(3)
    N, C, alpha, gamma = 12, 8, 0.24, 2.0
    x = rng.standard_normal((N, C)).astype(np.float32)
    cls = rng.integers(0, C, N)
    y = np.eye(C)[cls]

    got = FocalLoss.apply(jnp.asarray(x), jnp.asarray(cls), 1.0, C,
                          alpha, gamma, 0.0)
    np.testing.assert_allclose(float(got),
                               sigmoid_focal_oracle(x, y, alpha, gamma),
                               rtol=1e-5)


def test_focal_loss_negative_targets_and_normalizer():
    from apex_tpu.contrib.focal_loss import focal_loss

    rng = np.random.default_rng(4)
    N, C = 10, 5
    x = rng.standard_normal((N, C)).astype(np.float32)
    cls = np.full(N, -1)  # all background
    got = focal_loss(jnp.asarray(x), jnp.asarray(cls), 2.0, C, 0.25, 2.0)
    want = sigmoid_focal_oracle(x, np.zeros((N, C)), 0.25, 2.0) / 2.0
    np.testing.assert_allclose(float(got), want, rtol=1e-5)


def test_focal_loss_padded_classes_no_grad():
    from apex_tpu.contrib.focal_loss import focal_loss

    rng = np.random.default_rng(5)
    N, C_real, C_pad = 6, 7, 16
    x = jnp.asarray(rng.standard_normal((N, C_pad)), jnp.float32)
    cls = jnp.asarray(rng.integers(0, C_real, N))
    g = jax.grad(lambda x: focal_loss(x, cls, 1.0, C_real, 0.25, 2.0))(x)
    assert np.abs(np.asarray(g)[:, C_real:]).max() == 0.0
    assert np.abs(np.asarray(g)[:, :C_real]).max() > 0.0


def test_focal_loss_label_smoothing_changes_targets():
    from apex_tpu.contrib.focal_loss import focal_loss

    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)
    cls = jnp.asarray(rng.integers(0, 6, 4))
    a = float(focal_loss(x, cls, 1.0, 6, 0.25, 2.0, 0.0))
    b = float(focal_loss(x, cls, 1.0, 6, 0.25, 2.0, 0.1))
    assert a != b


# ---------------------------------------------------------------------------
# transducer
# ---------------------------------------------------------------------------

def transducer_oracle(x, label, f_len, y_len, blank):
    """Python port of the DP in _transducer_ref.py:4-76 (loss + dlogp)."""
    def lse(a, b):
        m = max(a, b)
        return m + np.log(np.exp(a - m) + np.exp(b - m))

    x = np.asarray(x, np.float64)
    m = x.max(-1, keepdims=True)
    logp = x - m - np.log(np.exp(x - m).sum(-1, keepdims=True))
    B, T, U, V = x.shape
    alpha = np.zeros((B, T, U))
    beta = np.zeros((B, T, U))
    for b in range(B):
        fl, yl = f_len[b], y_len[b]
        for t in range(1, fl):
            alpha[b, t, 0] = alpha[b, t - 1, 0] + logp[b, t - 1, 0, blank]
        for u in range(1, yl + 1):
            alpha[b, 0, u] = alpha[b, 0, u - 1] + logp[b, 0, u - 1, label[b, u - 1]]
        for t in range(1, fl):
            for u in range(1, yl + 1):
                alpha[b, t, u] = lse(
                    alpha[b, t - 1, u] + logp[b, t - 1, u, blank],
                    alpha[b, t, u - 1] + logp[b, t, u - 1, label[b, u - 1]])
        beta[b, fl - 1, yl] = logp[b, fl - 1, yl, blank]
        for t in range(fl - 2, -1, -1):
            beta[b, t, yl] = beta[b, t + 1, yl] + logp[b, t, yl, blank]
        for u in range(yl - 1, -1, -1):
            beta[b, fl - 1, u] = beta[b, fl - 1, u + 1] + logp[b, fl - 1, u, label[b, u]]
        for t in range(fl - 2, -1, -1):
            for u in range(yl - 1, -1, -1):
                beta[b, t, u] = lse(
                    beta[b, t + 1, u] + logp[b, t, u, blank],
                    beta[b, t, u + 1] + logp[b, t, u, label[b, u]])
    loss = -beta[:, 0, 0]

    # gradient wrt logits for sum(loss)  (loss_grad = 1)
    dlogp = np.zeros_like(logp)
    for b in range(B):
        fl, yl = f_len[b], y_len[b]
        com = alpha[b] - beta[b, 0, 0]
        for u in range(yl):
            for t in range(fl):
                dlogp[b, t, u, label[b, u]] = -np.exp(
                    com[t, u] + beta[b, t, u + 1] + logp[b, t, u, label[b, u]])
        for t in range(fl - 1):
            for u in range(yl + 1):
                dlogp[b, t, u, blank] = -np.exp(
                    com[t, u] + beta[b, t + 1, u] + logp[b, t, u, blank])
        dlogp[b, fl - 1, yl, blank] = -np.exp(
            com[fl - 1, yl] + logp[b, fl - 1, yl, blank])
    dx = dlogp - np.exp(logp) * dlogp.sum(-1, keepdims=True)
    return loss, dx


def _rand_transducer(rng, B=3, T=7, Umax=4, V=6):
    y_len = rng.integers(1, Umax, B)
    f_len = rng.integers(Umax + 1, T + 1, B)  # f_len > y_len always
    U = int(y_len.max()) + 1
    x = rng.standard_normal((B, T, U, V)).astype(np.float32)
    label = rng.integers(1, V, (B, U - 1))
    return x, label, f_len, y_len


def test_transducer_loss_forward():
    from apex_tpu.contrib.transducer import TransducerLoss

    rng = np.random.default_rng(7)
    x, label, f_len, y_len = _rand_transducer(rng)
    want, _ = transducer_oracle(x, label, f_len, y_len, blank=0)
    got = TransducerLoss()(jnp.asarray(x), jnp.asarray(label),
                           jnp.asarray(f_len), jnp.asarray(y_len), 0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_transducer_loss_grad():
    from apex_tpu.contrib.transducer import transducer_loss

    rng = np.random.default_rng(8)
    x, label, f_len, y_len = _rand_transducer(rng)
    _, want = transducer_oracle(x, label, f_len, y_len, blank=0)
    got = jax.grad(lambda x: transducer_loss(
        x, jnp.asarray(label), jnp.asarray(f_len), jnp.asarray(y_len),
        0).sum())(jnp.asarray(x))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)


def test_transducer_loss_jits_and_batches():
    from apex_tpu.contrib.transducer import transducer_loss

    rng = np.random.default_rng(9)
    x, label, f_len, y_len = _rand_transducer(rng, B=5, T=9, Umax=5, V=8)
    fn = jax.jit(lambda x: transducer_loss(
        x, jnp.asarray(label), jnp.asarray(f_len), jnp.asarray(y_len), 0))
    want, _ = transducer_oracle(x, label, f_len, y_len, blank=0)
    np.testing.assert_allclose(fn(jnp.asarray(x)), want, rtol=1e-4, atol=1e-5)


def test_transducer_joint():
    from apex_tpu.contrib.transducer import TransducerJoint

    rng = np.random.default_rng(10)
    B, T, U, H = 3, 6, 4, 8
    f = rng.standard_normal((B, T, H)).astype(np.float32)
    g = rng.standard_normal((B, U, H)).astype(np.float32)
    f_len = np.array([6, 4, 5])
    g_len = np.array([4, 2, 3])

    h = TransducerJoint(relu=True)(jnp.asarray(f), jnp.asarray(g),
                                   jnp.asarray(f_len), jnp.asarray(g_len))
    want = np.maximum(f[:, :, None] + g[:, None], 0.0)
    for b in range(B):
        want[b, f_len[b]:] = 0.0
        want[b, :, g_len[b]:] = 0.0
    np.testing.assert_allclose(h, want, rtol=1e-6)


def test_transducer_joint_packed():
    from apex_tpu.contrib.transducer import TransducerJoint

    rng = np.random.default_rng(11)
    B, T, U, H = 3, 5, 4, 8
    f = rng.standard_normal((B, T, H)).astype(np.float32)
    g = rng.standard_normal((B, U, H)).astype(np.float32)
    f_len = np.array([5, 3, 4])
    g_len = np.array([4, 2, 3])
    batch_offset = np.cumsum(f_len * g_len)
    packed = int(batch_offset[-1])

    got = TransducerJoint(pack_output=True)(
        jnp.asarray(f), jnp.asarray(g), jnp.asarray(f_len),
        jnp.asarray(g_len), batch_offset=jnp.asarray(batch_offset),
        packed_batch=packed)
    assert got.shape == (packed, H)

    rows = []
    for b in range(B):
        for t in range(f_len[b]):
            for u in range(g_len[b]):
                rows.append(f[b, t] + g[b, u])
    np.testing.assert_allclose(got, np.stack(rows), rtol=1e-6)
