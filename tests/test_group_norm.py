"""contrib.group_norm vs torch.nn.functional.group_norm (the reference's
fallback oracle, apex/contrib/group_norm/group_norm.py:138-147)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc


def torch_oracle(x_nhwc, G, weight, bias, eps, act):
    import torch

    x = torch.from_numpy(np.moveaxis(x_nhwc, -1, 1).copy())  # NHWC -> NCHW
    y = torch.nn.functional.group_norm(
        x, G, torch.from_numpy(weight), torch.from_numpy(bias), eps)
    if act:
        y = y * torch.sigmoid(y)
    return np.moveaxis(y.numpy(), 1, -1)


@pytest.mark.parametrize("act", [None, "swish"])
@pytest.mark.parametrize("G,C", [(16, 128), (32, 320), (4, 20)])
def test_group_norm_matches_torch(G, C, act):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 7, C)).astype(np.float32)
    w = rng.standard_normal(C).astype(np.float32)
    b = rng.standard_normal(C).astype(np.float32)

    got = group_norm_nhwc(jnp.asarray(x), G, jnp.asarray(w), jnp.asarray(b),
                          1e-5, act)
    np.testing.assert_allclose(got, torch_oracle(x, G, w, b, 1e-5, act),
                               rtol=2e-5, atol=2e-5)


def test_group_norm_bf16_input_fp32_stats():
    rng = np.random.default_rng(1)
    # large offset would break bf16-accumulated statistics
    x = (rng.standard_normal((2, 4, 4, 64)) + 100.0).astype(np.float32)
    w = np.ones(64, np.float32)
    b = np.zeros(64, np.float32)
    x_bf16 = jnp.asarray(x, jnp.bfloat16)
    got = group_norm_nhwc(x_bf16, 8, jnp.asarray(w), jnp.asarray(b),
                          1e-5, None)
    assert got.dtype == jnp.bfloat16
    # oracle on the SAME quantized input: the comparison then measures the
    # statistics accumulation, not bf16 input rounding
    want = torch_oracle(np.asarray(x_bf16, np.float32), 8, w, b, 1e-5, None)
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               rtol=0.1, atol=0.1)
    # normalized output: near-zero mean despite the +100 offset
    assert abs(float(jnp.mean(got.astype(jnp.float32)))) < 0.05


def test_group_norm_module_and_grad():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 3, 3, 32)), jnp.float32)
    m = GroupNorm(num_groups=8, num_channels=32, act="silu")
    params = m.init(jax.random.PRNGKey(0), x)

    def loss(p, x):
        return jnp.sum(m.apply(p, x) ** 2)

    g = jax.grad(loss)(params, x)
    leaves = jax.tree.leaves(g)
    assert all(np.all(np.isfinite(l)) for l in leaves)
    assert any(np.abs(l).max() > 0 for l in leaves)


def test_group_norm_validation():
    with pytest.raises(ValueError):
        group_norm_nhwc(jnp.zeros((1, 2, 2, 10)), 3)
    with pytest.raises(ValueError):
        group_norm_nhwc(jnp.zeros((1, 2, 2, 8)), 2, act="relu")
