"""The Megatron-style GPT pretrain driver runs end-to-end on a 3D mesh."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_pretrain_driver_3d_mesh():
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "gpt" / "pretrain.py"),
         "--num-layers", "2", "--hidden-size", "32",
         "--num-attention-heads", "2", "--seq-length", "16",
         "--max-position-embeddings", "16", "--vocab-size", "64",
         "--micro-batch-size", "2", "--global-batch-size", "8",
         "--lr", "1e-3", "--train-iters", "3", "--optimizer", "lamb",
         "--tensor-model-parallel-size", "2",
         "--pipeline-model-parallel-size", "2"],
        capture_output=True, text=True, timeout=600,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
             "PYTHONPATH": str(REPO),
             "HOME": "/root"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "pretrain OK: dp=2 pp=2 tp=2" in out.stdout
