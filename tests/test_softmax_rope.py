"""Parity tests for the fused softmax family and RoPE (mirrors
tests/L0/run_transformer/test_fused_softmax.py and test_fused_rope.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_2d,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
)
from apex_tpu.ops.softmax import (
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)


def _ref_softmax(x, scale, mask=None, causal=False):
    x32 = np.asarray(x, np.float32) * scale
    b, h, sq, sk = x32.shape
    if causal:
        tri = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        x32 = np.where(tri, x32, -10000.0)
    if mask is not None:
        x32 = np.where(np.asarray(mask), -10000.0, x32)
    e = np.exp(x32 - x32.max(-1, keepdims=True))
    y = e / e.sum(-1, keepdims=True)
    if mask is not None:
        y = np.where(np.asarray(mask).all(-1, keepdims=True), 0.0, y)
    return y


def test_scaled_softmax(rng):
    x = jnp.asarray(rng.standard_normal((2, 4, 16, 32)), jnp.float32)
    y = scaled_softmax(x, 0.7)
    np.testing.assert_allclose(np.asarray(y), _ref_softmax(x, 0.7), rtol=1e-5, atol=1e-6)


def test_scaled_masked_softmax(rng):
    x = jnp.asarray(rng.standard_normal((2, 4, 16, 32)), jnp.float32)
    mask = jnp.asarray(rng.random((2, 1, 16, 32)) < 0.3)
    y = scaled_masked_softmax(x, mask, 1.3)
    np.testing.assert_allclose(np.asarray(y), _ref_softmax(x, 1.3, mask=mask),
                               rtol=1e-5, atol=1e-6)
    # fully masked row → zeros
    mask_all = mask.at[0, 0, 3, :].set(True)
    y2 = scaled_masked_softmax(x, mask_all, 1.3)
    np.testing.assert_allclose(np.asarray(y2[0, :, 3, :]), 0.0)


def test_causal_softmax_and_grad(rng):
    x = jnp.asarray(rng.standard_normal((2, 2, 8, 8)), jnp.float32)
    y = scaled_upper_triang_masked_softmax(x, 0.5)
    np.testing.assert_allclose(np.asarray(y), _ref_softmax(x, 0.5, causal=True),
                               rtol=1e-5, atol=1e-6)
    # grad parity vs autodiff-through-jnp reference
    def ref(x):
        x32 = x * 0.5
        sq, sk = x.shape[-2], x.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        return jnp.sum(jax.nn.softmax(jnp.where(tri, x32, -10000.0)) ** 2)

    g_f = jax.grad(lambda x: jnp.sum(scaled_upper_triang_masked_softmax(x, 0.5) ** 2))(x)
    g_r = jax.grad(ref)(x)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_r), rtol=1e-4, atol=1e-5)


def test_generic_matches_masked(rng):
    x = jnp.asarray(rng.standard_normal((2, 3, 7, 19)), jnp.float32)  # odd sizes
    mask = jnp.asarray(rng.random((2, 1, 7, 19)) < 0.2)
    y = generic_scaled_masked_softmax(x, mask, 0.9)
    np.testing.assert_allclose(np.asarray(y), _ref_softmax(x, 0.9, mask=mask),
                               rtol=1e-5, atol=1e-6)


def test_pallas_softmax_interpret(rng, monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "interpret")
    x = jnp.asarray(rng.standard_normal((2, 2, 128, 128)), jnp.float32)
    y = scaled_upper_triang_masked_softmax(x, 0.6)
    np.testing.assert_allclose(np.asarray(y), _ref_softmax(x, 0.6, causal=True),
                               rtol=1e-5, atol=1e-6)
    mask = jnp.asarray(rng.random((2, 1, 128, 128)) < 0.3)
    ym = scaled_masked_softmax(x, mask, 1.1)
    np.testing.assert_allclose(np.asarray(ym), _ref_softmax(x, 1.1, mask=mask),
                               rtol=1e-5, atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(scaled_softmax(x, 2.0) ** 2))(x)
    assert np.isfinite(np.asarray(g)).all()


# --- RoPE ------------------------------------------------------------------


def _ref_rope(t, freqs):
    t = np.asarray(t, np.float32)
    d2 = freqs.shape[-1]
    cos, sin = np.cos(np.asarray(freqs)), np.sin(np.asarray(freqs))
    tr = t[..., :d2]
    half = d2 // 2
    rot = np.concatenate([-tr[..., half:], tr[..., :half]], -1)
    out = tr * cos + rot * sin
    return np.concatenate([out, t[..., d2:]], -1)


@pytest.mark.parametrize("d2", [32, 16])
def test_rope_sbhd(rng, d2):
    t = jnp.asarray(rng.standard_normal((12, 2, 4, 32)), jnp.float32)
    freqs = jnp.asarray(rng.standard_normal((12, 1, 1, d2)), jnp.float32)
    y = fused_apply_rotary_pos_emb(t, freqs)
    np.testing.assert_allclose(np.asarray(y), _ref_rope(t, freqs), rtol=1e-5, atol=1e-5)
    # cached variant agrees
    y2 = fused_apply_rotary_pos_emb_cached(t, jnp.cos(freqs), jnp.sin(freqs))
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=1e-6, atol=1e-6)


def test_rope_thd(rng):
    # pack 3 sequences of lengths 4, 7, 5
    lens = [4, 7, 5]
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    total = sum(lens)
    t = jnp.asarray(rng.standard_normal((total, 2, 16)), jnp.float32)
    freqs = jnp.asarray(rng.standard_normal((8, 1, 1, 16)), jnp.float32)
    y = fused_apply_rotary_pos_emb_thd(t, cu, freqs)
    # reference: apply per-sequence sbhd rope with position restart
    out = []
    start = 0
    for L in lens:
        seq = np.asarray(t[start:start + L])[:, None]  # [s, 1, h, d]
        out.append(_ref_rope(seq, np.asarray(freqs[:L]))[:, 0])
        start += L
    np.testing.assert_allclose(np.asarray(y), np.concatenate(out, 0), rtol=1e-5, atol=1e-5)


def test_rope_2d(rng):
    b, ih, iw, h, d = 2, 4, 3, 2, 16
    t = jnp.asarray(rng.standard_normal((b, ih * iw, h, d)), jnp.float32)
    ang_h = rng.standard_normal((1, 6, 1, d // 2)).astype(np.float32)
    ang_w = rng.standard_normal((1, 5, 1, d // 2)).astype(np.float32)
    y = fused_apply_rotary_pos_emb_2d(
        t, ih, iw,
        jnp.cos(ang_h), jnp.sin(ang_h), jnp.cos(ang_w), jnp.sin(ang_w))
    assert y.shape == t.shape
    # reference: height rope on first d/2 channels (indexed by row), width on rest
    t5 = np.asarray(t).reshape(b, ih, iw, h, d)
    exp = np.empty_like(t5)
    for r in range(ih):
        exp[:, r, :, :, :d // 2] = _ref_rope(
            t5[:, r, :, :, :d // 2],
            np.broadcast_to(ang_h[:, r:r + 1, :, :], (1, 1, 1, d // 2)))
    for c in range(iw):
        exp[:, :, c, :, d // 2:] = _ref_rope(
            t5[:, :, c, :, d // 2:],
            np.broadcast_to(ang_w[:, c:c + 1, :, :], (1, 1, 1, d // 2)))
    np.testing.assert_allclose(np.asarray(y).reshape(exp.shape), exp, rtol=1e-5, atol=1e-5)


def test_causal_dispatcher_keeps_triangle_with_mask(rng):
    """ADVICE r1: causal FusedScaleMaskSoftmax given a padding-only mask must
    still apply the causal triangle (the reference asserts instead; we
    compose)."""
    from apex_tpu.transformer.enums import AttnMaskType
    from apex_tpu.transformer.functional import FusedScaleMaskSoftmax

    x = jnp.asarray(rng.standard_normal((2, 2, 8, 8)), jnp.float32)
    pad = jnp.zeros((2, 1, 8, 8), bool).at[:, :, :, 6:].set(True)
    probs = FusedScaleMaskSoftmax(
        attn_mask_type=AttnMaskType.causal, scale=0.5)(x, pad)
    p = np.asarray(probs)
    # future positions (col > row) must carry zero probability
    for r in range(8):
        assert np.all(p[:, :, r, r + 1:] < 1e-6), r
    # padding columns masked too
    assert np.all(p[:, :, :, 6:] < 1e-6)
    # kept rows still normalize
    np.testing.assert_allclose(p[:, :, 1:, :].sum(-1), 1.0, rtol=1e-5)
