"""Online serving ops: hot weight reload, rollback, shadow/A-B (ISSUE 16).

THE acceptance run: while an ``AsyncCheckpointer`` publishes new steps,
a scheduler drains a bursty open-loop workload on a virtual clock and
hot-reloads mid-stream — **zero dropped streams**, post-swap tokens
**bit-identical** to a fresh engine booted on the new weights and fed
the same state, a corrupted candidate refused with the old weights
served bit-exactly, and ``rollback()`` bit-exact — on dense and paged
engines, tp=1 and tp=2.

Plus: the watcher/writer race (a re-save swaps the committed dir aside
mid-commit; ``latest_valid_step`` and the serving-side walk must skip
live-writer steps, never crash, never select a partial dir), boot-time
degraded start (newest corrupt → fallback, later hot reload picks up
the repaired step), prefix-cache version invalidation across a swap,
seed-deterministic shadow/A-B with per-arm SLO reports that reconcile
against the request-trace recorder, and the house default-off rules:
byte-for-byte identity when nothing reload-shaped is constructed, and
zero new compiles per program family across a swap.
"""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import resilience as rz
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs import bridge as obs_bridge
from apex_tpu.obs import request_trace as rt
from apex_tpu.resilience import checkpoint as _ckpt
from apex_tpu.resilience.fault_injection import (
    CrashCheckpointWriter,
    FaultInjector,
    FaultPlan,
    ReloadStorm,
)
from apex_tpu.serving.engine import TPConfig
from apex_tpu.serving.paged_kv_cache import PagedCacheConfig
from apex_tpu.serving.prefix_cache import PrefixCacheConfig
from apex_tpu.utils.compat import device_count_skip_reason, devices_available

# GQA on purpose, like test_serving.py: kv_heads (2) < heads (4)
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


@pytest.fixture(scope="module")
def params_v2(params):
    """A genuinely different weight version (greedy argmaxes move)."""
    return _mutated(params, 0.05)


def _mutated(tree, delta):
    return jax.tree.map(
        lambda l: l + delta if jnp.issubdtype(l.dtype, jnp.floating)
        else l, tree)


def _prompt(seed=0, n=10):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(1, CFG.vocab_size, n)]


def _tree_bytes_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(fa, fb))


def _save_versions(root, params, *steps):
    """Commit train-state checkpoints {params: params + step/1000}."""
    for s in steps:
        rz.save_checkpoint(str(root), s,
                           {"params": _mutated(params, s / 1000.0)})


class _EventTap:
    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


def _engine(model, params, *, paged=False, tp=None, slots=4):
    kw = {}
    if paged:
        kw["paged"] = PagedCacheConfig(block_size=16, num_blocks=64)
    if tp is not None:
        kw["tp"] = TPConfig(size=tp)
    return sv.DecodeEngine(model, params, slots=slots, max_len=MAX,
                           prefill_len=16, **kw)


def _workload(n=6, burst=3, seed=0, max_new=8):
    return sv.make_workload(
        sv.zero_overlap_prompts(n, length=8, vocab=CFG.vocab_size,
                                seed=seed),
        sv.burst_arrivals(n, burst=burst, period_s=0.5),
        max_new_tokens=max_new)


# ---------------------------------------------------------------------------
# engine-level swap: the bit-identity core
# ---------------------------------------------------------------------------


class TestEngineSwap:
    def test_post_swap_tokens_bit_identical_to_fresh_engine_same_state(
            self, model, params, params_v2):
        """THE core claim: decode k tokens on old weights, swap, decode
        m more — the post-swap tokens (and logits, byte for byte) equal
        a FRESH engine booted on the new weights and fed the captured
        state.  Decode state is weight-independent; the swap touches
        nothing else."""
        eng = _engine(model, params, slots=2)
        prompt = _prompt(seed=1)
        logits = eng.prefill(0, prompt)
        stream = [int(jnp.argmax(logits))]
        toks = np.zeros((eng.slots,), np.int32)
        act = np.zeros((eng.slots,), bool)
        act[0] = True
        for _ in range(4):                       # old-weights tokens
            toks[0] = stream[-1]
            stream.append(int(jnp.argmax(eng.decode(toks, act)[0])))
        k, v, length = eng.capture_slot(0)       # the state at the swap

        old = eng.swap_params(params_v2)
        assert eng.weights_version == 1
        assert _tree_bytes_equal(old, params)    # displaced buffer intact
        post, post_logits = [], []
        for _ in range(6):                       # new-weights tokens
            toks[0] = (post[-1] if post else stream[-1])
            lg = eng.decode(toks, act)[0]
            post_logits.append(np.asarray(lg))
            post.append(int(jnp.argmax(lg)))

        fresh = _engine(model, params_v2, slots=2)
        fresh.restore_prefix(0, (k, v), length)
        ref, ref_logits = [], []
        for _ in range(6):
            toks[0] = (ref[-1] if ref else stream[-1])
            lg = fresh.decode(toks, act)[0]
            ref_logits.append(np.asarray(lg))
            ref.append(int(jnp.argmax(lg)))
        assert post == ref
        for a, b in zip(post_logits, ref_logits):
            np.testing.assert_array_equal(a, b)
        # and the streams actually changed across versions — the swap
        # did something (params_v2 is a real different model)
        assert eng.weights_version == 1

    def test_swap_is_zero_new_compiles_per_family(self, model, params,
                                                  params_v2, tmp_path):
        eng = _engine(model, params, slots=2)
        prompt = _prompt(seed=2)
        eng.prefill(0, prompt)
        toks = np.zeros((eng.slots,), np.int32)
        act = np.zeros((eng.slots,), bool)
        act[0] = True
        eng.decode(toks, act)
        pre_prefill = eng.prefill_compiles()
        assert eng.decode_compiles() == 1
        eng.swap_params(params_v2)
        eng.decode(toks, act)                    # same program, new tree
        eng.prefill(1, _prompt(seed=3))
        assert eng.decode_compiles() == 1        # THE zero-compile swap
        assert eng.prefill_compiles() == pre_prefill
        # the provenance that actually bites: the engine booted on
        # model.init params (uncommitted placement) and the candidate
        # came through the checkpoint-restore path (device_put =
        # committed placement).  jit keys its executable cache on
        # placement, so without the engine pinning params at boot this
        # swap retraced every program family once.
        _save_versions(tmp_path, params, 7)
        restored, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params")
        eng.swap_params(restored)
        eng.decode(toks, act)
        eng.release(1)
        eng.prefill(1, _prompt(seed=3))
        assert eng.decode_compiles() == 1
        assert eng.prefill_compiles() == pre_prefill

    def test_swap_rejects_mismatched_candidate(self, model, params):
        eng = _engine(model, params, slots=2)
        wrong_shape = jax.tree.map(
            lambda l: jnp.zeros(l.shape + (1,), l.dtype)
            if jnp.issubdtype(l.dtype, jnp.floating) else l, params)
        with pytest.raises(ValueError):
            eng.swap_params(wrong_shape)
        wrong_dtype = jax.tree.map(
            lambda l: l.astype(jnp.float16)
            if jnp.issubdtype(l.dtype, jnp.floating) else l, params)
        with pytest.raises(ValueError):
            eng.swap_params(wrong_dtype)
        with pytest.raises(ValueError):
            eng.swap_params({"nope": 1})
        assert eng.weights_version == 0          # nothing swapped


# ---------------------------------------------------------------------------
# WeightWatcher: the three committed-step sources
# ---------------------------------------------------------------------------


class TestWeightWatcher:
    def test_root_walk_source_and_monotonic_poll(self, params, tmp_path):
        w = sv.WeightWatcher(str(tmp_path))
        assert w.poll() is None                  # empty root: nothing
        _save_versions(tmp_path, params, 3, 7)
        assert w.poll() == 7                     # newest committed
        w.mark(7)
        assert w.poll() is None                  # nothing newer
        _save_versions(tmp_path, params, 9)
        assert w.poll() == 9
        # a refused candidate is re-offered: mark() was never called
        assert w.poll() == 9

    def test_checkpointer_source(self, params, tmp_path):
        ac = rz.AsyncCheckpointer(rz.CheckpointManager(str(tmp_path)))
        w = sv.WeightWatcher(str(tmp_path), checkpointer=ac)
        assert w.poll() is None                  # nothing committed yet
        fut = ac.save(12, {"params": params})
        fut.result()
        assert ac.committed_step == 12           # the new surface
        assert w.poll() == 12
        w.mark(12)
        assert w.poll() is None

    def test_heartbeat_source(self, params, tmp_path):
        hb = str(tmp_path / "heartbeat")
        root = str(tmp_path / "ckpts")
        _save_versions(root, params, 5)
        w = sv.WeightWatcher(root, heartbeat_path=hb)
        assert w.poll() is None                  # no heartbeat yet: no-op
        ckpt_path = os.path.join(root, _ckpt._step_dirname(5))
        rz.write_heartbeat(hb, 5, ckpt_path=ckpt_path)
        assert w.poll() == 5
        # heartbeat with no ckpt_path (training hasn't committed yet)
        rz.write_heartbeat(hb, 6)
        w2 = sv.WeightWatcher(root, heartbeat_path=hb)
        assert w2.poll() is None

    def test_one_source_only(self, tmp_path):
        ac = rz.AsyncCheckpointer(rz.CheckpointManager(str(tmp_path)))
        with pytest.raises(ValueError):
            sv.WeightWatcher(str(tmp_path), heartbeat_path="x",
                             checkpointer=ac)

    def test_walk_skips_live_writer_steps(self, params, tmp_path):
        """The registry contract: a step a live writer is mid-commit on
        is invisible to the watcher (and to latest_valid_step)."""
        _save_versions(tmp_path, params, 1, 4)
        w = sv.WeightWatcher(str(tmp_path))
        with _ckpt._live_writer(str(tmp_path), 4):
            assert _ckpt.in_flight_steps(str(tmp_path)) == {4}
            assert w.poll() == 1                 # 4 is mid-commit
            assert rz.latest_valid_step(str(tmp_path)) == 1
        assert w.poll() == 4                     # committed now


# ---------------------------------------------------------------------------
# satellite 2: the reload/writer race, concurrently
# ---------------------------------------------------------------------------


class TestWatcherWriterRace:
    def test_concurrent_resave_never_crashes_or_selects_partial(
            self, params, tmp_path):
        """A re-save of a committed step renames the final dir aside
        before installing the new one — a pre-fix reader validating
        that dir mid-swap crashed on FileNotFoundError.  Hammer
        latest_valid_step + the watcher against a loop of re-saves:
        no exception, and every answer is a step that was durably
        committed at some point (1 or 5), never a torn read."""
        root = str(tmp_path)
        mgr = rz.CheckpointManager(root, keep=8)
        mgr.save(1, {"params": params})
        mgr.save(5, {"params": params})
        stop = threading.Event()
        writer_err = []

        def writer():
            try:
                while not stop.is_set():
                    mgr.save(5, {"params": params})   # aside-swap path
            except BaseException as e:               # pragma: no cover
                writer_err.append(e)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            w = sv.WeightWatcher(root)
            for _ in range(300):
                got = rz.latest_valid_step(root)
                assert got in (1, 5)
                seen = w.committed_step()
                assert seen in (1, 5)
        finally:
            stop.set()
            t.join(30.0)
        assert not writer_err

    def test_writer_crash_leaves_watcher_blind_to_partial(
            self, params, tmp_path):
        """SimulatedWriterCrash racing the watcher: a writer killed
        mid-write leaves only a temp dir — the watcher (and the serving
        restore walk) must never see the step."""
        root = str(tmp_path)
        _save_versions(tmp_path, params, 2)
        crash = CrashCheckpointWriter(after_records=1)
        ac = rz.AsyncCheckpointer(rz.CheckpointManager(root),
                                  progress_hook=crash)
        fut = ac.save(6, {"params": params})
        fut.join()
        assert isinstance(fut.error, rz.SimulatedWriterCrash)
        w = sv.WeightWatcher(root)
        assert w.poll() == 2                     # 6 never committed
        assert rz.latest_valid_step(root) == 2
        got, step = sv.load_serving_params(root, {"params": params},
                                           params_key="params")
        assert step == 2
        ac2 = rz.AsyncCheckpointer(rz.CheckpointManager(root))
        ac2.save(6, {"params": params}).result()  # retry commits
        assert w.poll() == 6


# ---------------------------------------------------------------------------
# HotReloader: validate gate, refusal, rollback
# ---------------------------------------------------------------------------


def _sched(engine, clk=None, **kw):
    return sv.ContinuousBatchingScheduler(
        engine, max_queue=16, clock=clk or sv.VirtualClock(), **kw)


class TestHotReloader:
    def test_reload_swaps_and_events_carry_phases(self, model, params,
                                                  tmp_path):
        _save_versions(tmp_path, params, 100, 200)
        boot, step = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=100)
        eng = _engine(model, boot, slots=2)
        eng.prefill(0, _prompt(seed=9))          # warm decode program
        toks = np.zeros((eng.slots,), np.int32)
        act = np.zeros((eng.slots,), bool)
        act[0] = True
        eng.decode(toks, act)
        sched = _sched(eng)
        rl = sv.HotReloader(sched, str(tmp_path),
                            like={"params": params},
                            params_key="params", current_step=100)
        with _EventTap() as tap:
            out = rl.maybe_reload()
        assert out.ok and out.step == 200 and out.from_step == 100
        # the restored (committed-placement) candidate reuses the warm
        # program — an uncommitted boot tree vs committed restore
        # placement flip would retrace here
        eng.decode(toks, act)
        assert eng.decode_compiles() == 1
        eng.release(0)
        assert rl.current_step == 200 and rl.previous_step == 100
        assert eng.weights_version == 1
        (loaded,) = tap.of("serving_weights_loaded")
        assert loaded["step"] == 200 and loaded["bytes"] > 0
        assert loaded["duration_s"] >= 0
        assert loaded["format_version"] == 1
        (swapped,) = tap.of("serving_weights_swapped")
        assert swapped["step"] == 200 and swapped["from_step"] == 100
        assert swapped["rollback"] is False
        for phase in ("restore_s", "validate_s", "swap_s"):
            assert swapped[phase] >= 0
        assert rl.maybe_reload() is None         # steady state: no-op
        assert rl.stats["reloads"] == 1

    def test_corrupt_candidate_refused_old_weights_bit_exact(
            self, model, params, tmp_path):
        """Failed validate never serves: corrupt AND truncated
        candidates refuse the swap with the serving params bit-exactly
        untouched, and the stream keeps decoding on the old weights."""
        _save_versions(tmp_path, params, 100)
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params")
        eng = _engine(model, boot, slots=2)
        sched = _sched(eng)
        rl = sv.HotReloader(sched, str(tmp_path),
                            like={"params": params},
                            params_key="params", current_step=100)
        before = jax.tree.map(lambda l: np.asarray(l).copy(), eng.params)
        fi = FaultInjector(FaultPlan(seed=0))

        _save_versions(tmp_path, params, 200)
        fi.corrupt_checkpoint(
            os.path.join(str(tmp_path), _ckpt._step_dirname(200)))
        with _EventTap() as tap:
            out = rl.reload(step=200)
        assert not out.ok and out.reason
        assert rl.current_step == 100 and not rl.can_rollback
        assert eng.weights_version == 0
        assert _tree_bytes_equal(eng.params, before)
        (failed,) = tap.of("serving_reload_failed")
        assert failed["step"] == 200 and failed["serving_step"] == 100

        _save_versions(tmp_path, params, 300)
        fi.truncate_checkpoint(
            os.path.join(str(tmp_path), _ckpt._step_dirname(300)))
        out = rl.reload(step=300)
        assert not out.ok
        assert _tree_bytes_equal(eng.params, before)
        assert rl.stats["refusals"] == 2

        # the watcher keeps re-offering the refused step until it is
        # repaired — then the reload goes through (satellite 3's
        # repaired-step pickup)
        assert rl.watcher.poll() == 300
        rz.save_checkpoint(str(tmp_path), 300,
                           {"params": _mutated(params, 0.3)})
        out = rl.maybe_reload()
        assert out.ok and out.step == 300
        assert rl.current_step == 300

    def test_spec_mismatch_refused_not_raised(self, model, params,
                                              tmp_path):
        """A candidate with the wrong structure refuses (ok=False), it
        does not throw — the server must keep serving."""
        _save_versions(tmp_path, params, 100)
        wrong = jax.tree.map(
            lambda l: jnp.zeros(l.shape + (1,), l.dtype)
            if jnp.issubdtype(l.dtype, jnp.floating) else l, params)
        rz.save_checkpoint(str(tmp_path / "wrong"), 200,
                           {"params": wrong})
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params")
        eng = _engine(model, boot, slots=2)
        rl = sv.HotReloader(_sched(eng), str(tmp_path / "wrong"),
                            like={"params": wrong}, params_key="params")
        out = rl.reload(step=200)
        assert not out.ok and "leaf" in out.reason
        assert eng.weights_version == 0

    def test_rollback_bit_exact_and_toggles(self, model, params,
                                            tmp_path):
        _save_versions(tmp_path, params, 100, 200)
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=100)
        original = jax.tree.map(lambda l: np.asarray(l).copy(),
                                boot)
        eng = _engine(model, boot, slots=2)
        sched = _sched(eng)
        rl = sv.HotReloader(sched, str(tmp_path),
                            like={"params": params},
                            params_key="params", current_step=100)
        with pytest.raises(RuntimeError):
            rl.rollback()                        # nothing to roll back to
        assert rl.reload(step=200).ok
        with _EventTap() as tap:
            rb = rl.rollback()
        assert rb.ok and rb.rollback and rb.step == 100
        assert rl.current_step == 100 and rl.previous_step == 200
        assert _tree_bytes_equal(eng.params, original)   # bit-exact
        (ev,) = tap.of("serving_weights_swapped")
        assert ev["rollback"] is True and ev["step"] == 100
        assert "restore_s" not in ev and "validate_s" not in ev
        rb2 = rl.rollback()                      # toggles back forward
        assert rb2.ok and rb2.step == 200
        assert eng.weights_version == 3

    def test_rollback_discards_staged_prefetch(self, model, params,
                                               tmp_path):
        """ISSUE 18 satellite: a stage prefetched from the version
        line being abandoned dies with the rollback — a later reload()
        must NOT silently re-promote the rolled-back direction — and
        the discard is counted in ``stats['discarded_stages']``."""
        _save_versions(tmp_path, params, 100, 200, 300)
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=100)
        original = jax.tree.map(lambda l: np.asarray(l).copy(), boot)
        eng = _engine(model, boot, slots=2)
        rl = sv.HotReloader(_sched(eng), str(tmp_path),
                            like={"params": params},
                            params_key="params", current_step=100)
        assert rl.reload(step=200).ok
        assert rl.prefetch(step=300) == 300      # restore-ahead staged
        assert rl.staged_step == 300
        assert rl.stats["discarded_stages"] == 0
        rb = rl.rollback()
        assert rb.ok and rb.rollback and rb.step == 100
        # the stage belonged to the abandoned line: discarded, counted
        assert rl.staged_step is None
        assert rl.stats["discarded_stages"] == 1
        assert rl.current_step == 100
        assert _tree_bytes_equal(eng.params, original)

    def test_retry_policy_wraps_transient_io_only(self, model, params,
                                                  tmp_path):
        """Deterministic corruption propagates through retry_transient
        immediately (CheckpointError.transient is False) — the refusal
        path, not an I/O retry loop."""
        _save_versions(tmp_path, params, 100, 200)
        FaultInjector(FaultPlan(seed=1)).corrupt_checkpoint(
            os.path.join(str(tmp_path), _ckpt._step_dirname(200)))
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=100)
        eng = _engine(model, boot, slots=2)
        rl = sv.HotReloader(_sched(eng), str(tmp_path),
                            like={"params": params}, params_key="params",
                            current_step=100,
                            retry=rz.RetryPolicy(max_attempts=3))
        with _EventTap() as tap:
            out = rl.reload(step=200)
        assert not out.ok
        assert tap.of("retry_attempt") == []     # no retries burned


class TestPrefetch:
    """Restore-ahead staging (ISSUE 17 satellite): ``prefetch()`` pays
    restore+validate off the serving path so the boundary ``reload()``
    is swap-only."""

    def _reloader(self, model, params, tmp_path, *steps):
        _save_versions(tmp_path, params, *steps)
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=steps[0])
        eng = _engine(model, boot, slots=2)
        rl = sv.HotReloader(_sched(eng), str(tmp_path),
                            like={"params": params},
                            params_key="params",
                            current_step=steps[0])
        return eng, rl

    def test_prefetch_stages_and_reload_consumes_without_reading_disk(
            self, model, params, tmp_path):
        eng, rl = self._reloader(model, params, tmp_path, 100, 200)
        assert rl.staged_step is None
        assert rl.prefetch() == 200          # watcher-resolved target
        assert rl.staged_step == 200
        assert rl.stats["prefetches"] == 1
        assert rl.prefetch(step=200) == 200  # idempotent: no re-restore
        assert rl.stats["prefetches"] == 1
        # the staged buffer IS the candidate: corrupt the on-disk dir
        # after staging — a reload that consumed the stage cannot have
        # re-read it
        FaultInjector(FaultPlan(seed=3)).corrupt_checkpoint(
            os.path.join(str(tmp_path), _ckpt._step_dirname(200)))
        with _EventTap() as tap:
            out = rl.reload(step=200)
        assert out.ok and out.step == 200
        assert rl.staged_step is None        # stage consumed
        assert _tree_bytes_equal(eng.params, _mutated(params, 0.2))
        (ev,) = tap.of("serving_weights_swapped")
        assert ev["prefetched"] is True
        # the staged phase walls ride along (the work was real, it
        # just didn't stall serving)
        assert ev["restore_s"] > 0 and out.restore_s > 0

    def test_stale_stage_discarded_on_mismatched_target(
            self, model, params, tmp_path):
        eng, rl = self._reloader(model, params, tmp_path, 100, 200, 300)
        assert rl.prefetch(step=200) == 200
        with _EventTap() as tap:
            out = rl.reload(step=300)        # not what was staged
        assert out.ok and out.step == 300
        assert rl.staged_step is None        # stale stage dropped
        assert _tree_bytes_equal(eng.params, _mutated(params, 0.3))
        (ev,) = tap.of("serving_weights_swapped")
        assert ev["prefetched"] is False

    def test_prefetch_failure_is_none_not_a_refusal(
            self, model, params, tmp_path):
        eng, rl = self._reloader(model, params, tmp_path, 100, 200)
        FaultInjector(FaultPlan(seed=4)).corrupt_checkpoint(
            os.path.join(str(tmp_path), _ckpt._step_dirname(200)))
        with _EventTap() as tap:
            assert rl.prefetch(step=200) is None
        assert rl.staged_step is None
        assert rl.stats["prefetches"] == 0
        # nothing was offered for serving, so no first-class refusal —
        # the later reload() walks the full path and refuses there
        assert rl.stats["refusals"] == 0
        assert tap.of("serving_reload_failed") == []
        assert not rl.reload(step=200).ok
        assert rl.stats["refusals"] == 1

    def test_prefetch_no_committed_step_is_none(self, model, params,
                                                tmp_path):
        _save_versions(tmp_path, params, 100)
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params")
        eng = _engine(model, boot, slots=2)
        empty = str(tmp_path / "empty")
        os.makedirs(empty)
        rl = sv.HotReloader(_sched(eng), empty,
                            like={"params": params},
                            params_key="params", current_step=100)
        assert rl.prefetch() is None
        assert rl.staged_step is None


# ---------------------------------------------------------------------------
# THE acceptance run: reload mid-stream under bursty open-loop load
# ---------------------------------------------------------------------------


def _run_workload_with_swap(model, boot_params, new_params, *,
                            swap_step, paged=False, tp=None, seed=0,
                            prefix=False):
    """Drive a bursty open-loop workload on a virtual clock, swapping
    weights at scheduler step ``swap_step`` via the step hook; returns
    (results, engine, refused_or_ok_outcome)."""
    eng = _engine(model, boot_params, paged=paged, tp=tp)
    kw = {}
    if prefix:
        kw["prefix_caching"] = PrefixCacheConfig(block_size=16,
                                                 max_tokens=2048)
    sched = _sched(eng, **kw)
    outcome = []

    def hook(step, scheduler):
        if step == swap_step:
            outcome.append(sched.swap_weights(new_params))

    wl = _workload(seed=seed)
    out = sv.LoadGenerator(sched, wl, step_time_s=0.05,
                           step_hook=hook).run()
    assert out.rejected == []                    # queue sized to fit
    return out, eng, outcome


class TestAcceptanceRun:
    @pytest.mark.parametrize("paged", [False, True],
                             ids=["dense", "paged"])
    def test_mid_stream_swap_zero_dropped_streams(self, model, params,
                                                  params_v2, paged):
        """Every offered stream finishes normally across a mid-drain
        swap — nothing dropped, nothing cancelled — and the run is
        deterministic: an identical second run produces identical
        token streams."""
        out, eng, swapped = _run_workload_with_swap(
            model, params, params_v2, swap_step=2, paged=paged)
        assert len(swapped) == 1
        assert eng.weights_version == 1
        assert len(out.results) == 6             # ZERO dropped streams
        for r in out.results.values():
            assert r.finish_reason in ("eos", "length")
            assert len(r.tokens) > 0
        out2, _, _ = _run_workload_with_swap(
            model, params, params_v2, swap_step=2, paged=paged)
        assert {k: v.tokens for k, v in out.results.items()} == \
               {k: v.tokens for k, v in out2.results.items()}

    def test_paged_and_dense_streams_identical_across_swap(
            self, model, params, params_v2):
        """The paged engine's identity contract survives a hot swap:
        same workload, same swap step — dense and paged emit identical
        token streams."""
        dense, _, _ = _run_workload_with_swap(
            model, params, params_v2, swap_step=2, paged=False)
        paged, _, _ = _run_workload_with_swap(
            model, params, params_v2, swap_step=2, paged=True)
        assert {k: v.tokens for k, v in dense.results.items()} == \
               {k: v.tokens for k, v in paged.results.items()}

    def test_swap_actually_changes_streams(self, model, params,
                                           params_v2):
        """An honest witness that the swap serves the NEW weights: the
        swapped run's streams differ from a never-swapped run's (the
        mutation is big enough to move greedy argmaxes)."""
        swapped, _, _ = _run_workload_with_swap(
            model, params, params_v2, swap_step=1)
        plain_eng = _engine(model, params)
        plain = sv.LoadGenerator(_sched(plain_eng), _workload(),
                                 step_time_s=0.05).run()
        assert {k: v.tokens for k, v in swapped.results.items()} != \
               {k: v.tokens for k, v in plain.results.items()}

    @pytest.mark.slow   # ~5 s: tier-1 keeps the dense+paged mid-stream
    # swap zero-drop witnesses above plus the weights-onto-mesh restore
    # witnesses in test_serving_tp.py
    @pytest.mark.skipif(not devices_available(2),
                        reason=device_count_skip_reason(2))
    def test_tp2_swap_stream_identical_to_single_chip_swap(
            self, model, params, params_v2):
        """tp=2 under a mid-stream swap serves the same tokens as the
        single-chip engine under the same swap."""
        single, _, _ = _run_workload_with_swap(
            model, params, params_v2, swap_step=2)
        tp2, eng, _ = _run_workload_with_swap(
            model, params, params_v2, swap_step=2, tp=2)
        assert eng.tp_size == 2
        assert {k: v.tokens for k, v in single.results.items()} == \
               {k: v.tokens for k, v in tp2.results.items()}

    @pytest.mark.skipif(not devices_available(2),
                        reason=device_count_skip_reason(2))
    def test_tp2_reloader_restores_onto_mesh(self, model, params,
                                             tmp_path):
        """A tp engine's HotReloader derives the mesh shardings
        automatically: the candidate restores mesh-direct and the swap
        is a no-op placement."""
        _save_versions(tmp_path, params, 100, 200)
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=100)
        eng = _engine(model, boot, slots=2, tp=2)
        rl = sv.HotReloader(_sched(eng), str(tmp_path),
                            like={"params": params},
                            params_key="params", current_step=100)
        assert rl.shardings is not None          # derived from the mesh
        out = rl.reload(step=200)
        assert out.ok and eng.weights_version == 1
        assert eng.decode_compiles() <= 1

    def test_async_publisher_racing_live_drain(self, model, params,
                                               tmp_path):
        """The full loop: an AsyncCheckpointer commits new steps WHILE
        the scheduler drains a bursty workload; the reloader polls the
        checkpointer each step and hot-swaps when a commit lands.
        Zero dropped streams, and the engine ends on the final
        committed step."""
        root = str(tmp_path)
        _save_versions(tmp_path, params, 100)
        boot, _ = sv.load_serving_params(root, {"params": params},
                                         params_key="params")
        eng = _engine(model, boot)
        sched = _sched(eng)
        ac = rz.AsyncCheckpointer(rz.CheckpointManager(root, keep=8))
        rl = sv.HotReloader(
            sched, root, like={"params": params}, params_key="params",
            watcher=sv.WeightWatcher(root, checkpointer=ac),
            current_step=100)
        published = []

        def hook(step, scheduler):
            if step == 1:                        # training publishes...
                published.append(ac.save(200, {
                    "params": _mutated(params, 0.2)}))
            rl.maybe_reload()                    # ...serving polls

        wl = _workload()
        out = sv.LoadGenerator(sched, wl, step_time_s=0.05,
                               step_hook=hook).run()
        ac.wait()
        final = rl.maybe_reload()                # commit may land late
        assert rl.current_step == 200
        assert final is None or final.ok
        assert len(out.results) == 6
        for r in out.results.values():
            assert r.finish_reason in ("eos", "length")

    def test_reload_storm_under_overload(self, model, params, tmp_path):
        """Chaos: forced reload attempts at many step boundaries while
        a 2x-overload burst drains (queue sized so arrivals shed).
        Streams that were admitted all finish; the storm's outcome log
        matches the engine's version count; accounting stays exact."""
        root = str(tmp_path)
        _save_versions(tmp_path, params, 100, 200, 300)
        boot, _ = sv.load_serving_params(root, {"params": params},
                                         params_key="params", step=100)
        eng = _engine(model, boot, slots=2)
        sched = sv.ContinuousBatchingScheduler(
            eng, max_queue=3, clock=sv.VirtualClock())
        rl = sv.HotReloader(sched, root, like={"params": params},
                            params_key="params", current_step=100)
        storm = ReloadStorm(range(0, 30, 2), reloader=rl, force=True)
        wl = sv.make_workload(
            sv.zero_overlap_prompts(10, length=8, vocab=CFG.vocab_size),
            sv.burst_arrivals(10, burst=5, period_s=0.1),
            max_new_tokens=6)
        out = sv.LoadGenerator(
            sched, wl, step_time_s=0.05,
            step_hook=sv.chain_hooks(None, storm)).run()
        assert len(storm.outcomes) >= 3
        oks = [o for o in storm.outcomes if o is not None and o.ok]
        assert len(oks) >= 1
        assert eng.weights_version == len(oks)
        # overload sheds arrivals (open-loop honesty) but every
        # ADMITTED stream survived the storm
        for r in out.results.values():
            assert r.finish_reason in ("eos", "length")
        assert len(out.results) + len(out.rejected) == 10
        assert sched.queue_depth == 0 and sched.active_count == 0
        sched.close()                            # accounting is clean


# ---------------------------------------------------------------------------
# satellite 3: boot-time degraded start, then repaired-step pickup
# ---------------------------------------------------------------------------


class TestDegradedStart:
    def test_boot_falls_back_then_hot_reload_picks_up_repair(
            self, model, params, tmp_path):
        root = str(tmp_path)
        _save_versions(tmp_path, params, 1, 2)
        FaultInjector(FaultPlan(seed=0)).corrupt_checkpoint(
            os.path.join(root, _ckpt._step_dirname(2)))
        with _EventTap() as tap:
            boot, step = sv.load_serving_params(
                root, {"params": params}, params_key="params")
        assert step == 1                         # degraded: newest is bad
        assert len(tap.of("checkpoint_rejected")) >= 1
        (loaded,) = tap.of("serving_weights_loaded")
        assert loaded["step"] == 1
        eng = _engine(model, boot, slots=2)
        rl = sv.HotReloader(_sched(eng), root, like={"params": params},
                            params_key="params", current_step=step)
        assert rl.watcher.poll() == 2            # still offered
        assert not rl.reload(step=2).ok          # still corrupt: refused
        rz.save_checkpoint(root, 2, {"params": _mutated(params, 0.002)})
        out = rl.maybe_reload()                  # repaired: picked up
        assert out.ok and out.step == 2
        assert rl.current_step == 2


# ---------------------------------------------------------------------------
# prefix-cache version invalidation
# ---------------------------------------------------------------------------


def _kv_region(seed, n):
    hd = CFG.hidden_size // CFG.num_attention_heads
    rng = np.random.default_rng(seed)
    shape = (CFG.num_hidden_layers, n, CFG.kv_heads, hd)
    return (jnp.asarray(rng.standard_normal(shape), jnp.float32),
            jnp.asarray(rng.standard_normal(shape), jnp.float32))


class TestPrefixCacheInvalidation:
    def test_bump_version_invalidates_match_and_reclaims(self):
        from apex_tpu.serving.prefix_cache import PrefixCache

        pc = PrefixCache(block_size=4, max_tokens=64)
        a = pc.put(PrefixCache.ROOT, [1, 2, 3, 4], *_kv_region(0, 4))
        pc.put(a.chain, [5, 6, 7, 8], *_kv_region(1, 4))
        probe = [1, 2, 3, 4, 5, 6, 7, 8, 9]      # 8 cached + next token
        assert pc.match(probe)[0] == 8
        v1 = pc.bump_version()
        assert v1 == 1 and pc.version == 1
        assert pc.match(probe)[0] == 0           # stale: unmatchable
        # unpinned stale entries were dropped at the bump fixpoint
        assert pc.stale_entries == 0
        assert pc.stats()["version"] == 1

    def test_stale_pinned_entry_survives_then_drains(self):
        from apex_tpu.serving.prefix_cache import PrefixCache

        pc = PrefixCache(block_size=4, max_tokens=64)
        a = pc.put(PrefixCache.ROOT, [1, 2, 3, 4], *_kv_region(0, 4))
        pc.acquire([a])                          # a live pre-swap stream
        pc.bump_version()
        assert pc.stale_entries == 1             # pinned: storage survives
        assert pc.match([1, 2, 3, 4, 5])[0] == 0   # but never matches
        pc.release([a])
        pc.bump_version()                        # next sweep reclaims
        assert pc.stale_entries == 0

    def test_scheduler_swap_bumps_version_and_recaches(self, model,
                                                       params,
                                                       params_v2):
        """Prefix hits before the swap, version bump at the swap, and
        post-swap admissions repopulate under the new version — a
        post-swap stream never resumes from old-weights K/V: it serves
        exactly what a cold engine on the new weights serves."""
        shared = sv.shared_prefix_prompts(
            4, shared_len=32, suffix_len=4, vocab=CFG.vocab_size)
        eng = _engine(model, params)
        sched = _sched(eng, prefix_caching=PrefixCacheConfig(
            block_size=16, max_tokens=2048))
        for i in range(2):                       # sequential: a1 hits
            sched.submit(sv.Request(f"a{i}", shared[i],
                                    max_new_tokens=4))
            sched.run()
        stats = sched.prefix_cache.stats()
        assert stats["hits"] >= 1                # warm before the swap
        v0 = stats["version"]
        sched.swap_weights(params_v2)
        assert sched.prefix_cache.stats()["version"] == v0 + 1
        for i in range(2, 4):
            sched.submit(sv.Request(f"a{i}", shared[i],
                                    max_new_tokens=4))
            sched.run()
        # a2 could NOT hit the stale entries; a3 hits a2's
        # fresh-version capture
        stats = sched.prefix_cache.stats()
        assert stats["hits"] >= 2
        cold = _engine(model, params_v2, slots=2)
        cs = _sched(cold)
        cs.submit(sv.Request("a3", shared[3], max_new_tokens=4))
        want = cs.run()["a3"].tokens
        assert sched.results["a3"].tokens == want


# ---------------------------------------------------------------------------
# shadow / A-B serving
# ---------------------------------------------------------------------------


class TestShadowAB:
    def test_assign_arm_deterministic_and_fraction(self):
        got = [sv.assign_arm(f"r{i}", fraction=0.25, seed=7)
               for i in range(400)]
        again = [sv.assign_arm(f"r{i}", fraction=0.25, seed=7)
                 for i in range(400)]
        assert got == again                      # stable, no RNG state
        frac = sum(got) / len(got)
        assert 0.15 < frac < 0.35                # hash-uniform
        other = [sv.assign_arm(f"r{i}", fraction=0.25, seed=8)
                 for i in range(400)]
        assert got != other                      # seed moves the draw
        assert not any(sv.assign_arm(f"r{i}", fraction=0.0)
                       for i in range(50))
        assert all(sv.assign_arm(f"r{i}", fraction=1.0)
                   for i in range(50))
        with pytest.raises(ValueError):
            sv.assign_arm("r", fraction=1.5)

    def _ab(self, model, primary_params, shadow_params, fraction=0.5,
            seed=0):
        clk = sv.VirtualClock()
        primary = _sched(_engine(model, primary_params), clk)
        shadow = _sched(_engine(model, shadow_params), clk)
        return sv.ShadowABScheduler(
            primary, shadow,
            sv.ABConfig(fraction=fraction, seed=seed))

    @pytest.mark.slow   # ~4 s: tier-1 keeps the seed-deterministic
    # mirror + reconciling arm-reports witness of the A/B claim
    def test_identical_weights_arms_emit_identical_streams(self, model,
                                                           params):
        """The null experiment: candidate == incumbent weights ⇒ every
        mirror copy's stream is bit-identical to its original."""
        ab = self._ab(model, params, params)
        wl = _workload()
        out = sv.LoadGenerator(ab, wl, step_time_s=0.05).run()
        assert ab.mirrored_rids                  # fraction=0.5 hit some
        assert ab.mirror_shed == 0
        shadow_results = ab.shadow.results
        for rid in ab.mirrored_rids:
            assert out.results[rid].tokens == \
                shadow_results["shadow:" + rid].tokens

    def test_seed_deterministic_mirror_and_arm_reports_reconcile(
            self, model, params, params_v2):
        """Same seed ⇒ same mirrored set across runs; per-arm reports
        are built over exactly the recorder's records for that arm,
        and the candidate arm genuinely served the candidate
        weights."""
        clk_runs = []
        for _ in range(2):
            ab = self._ab(model, params, params_v2, fraction=0.5,
                          seed=3)
            rec = rt.RequestTraceRecorder(clock=ab.clock).install()
            try:
                out = sv.LoadGenerator(ab, _workload(),
                                       step_time_s=0.05).run()
            finally:
                rec.uninstall()
            clk_runs.append((ab, rec, out))
        (ab1, rec1, out1), (ab2, rec2, out2) = clk_runs
        assert ab1.mirrored_rids == ab2.mirrored_rids    # seed-stable
        n_mirror = len(ab1.mirrored_rids)
        assert 0 < n_mirror < 6

        arms = ab1.arm_records(rec1.records())
        # reconciliation: one candidate record per mirrored rid, one
        # incumbent record per mirrored rid — same traffic, both arms
        assert len(arms["candidate"]) == n_mirror
        assert len(arms["incumbent"]) == n_mirror
        assert sorted(r.rid for r in arms["incumbent"]) == \
            sorted(ab1.mirrored_rids)
        reports = ab1.arm_reports(rec1.records(),
                                  deadlines=out1.deadlines,
                                  arrivals=out1.arrivals,
                                  duration_s=out1.duration_s)
        for arm in ("incumbent", "candidate"):
            assert reports[arm].completed == n_mirror
            assert reports[arm].offered == n_mirror
        # different weights: at least one mirrored stream differs
        shadow_results = ab1.shadow.results
        diffs = [rid for rid in ab1.mirrored_rids
                 if out1.results[rid].tokens
                 != shadow_results["shadow:" + rid].tokens]
        assert diffs

    def test_users_only_see_incumbent_and_shadow_shed_is_silent(
            self, model, params, params_v2):
        """Facade results are the primary's alone; a full shadow queue
        drops only the mirror copy, never the original."""
        clk = sv.VirtualClock()
        primary = _sched(_engine(model, params), clk)
        shadow = sv.ContinuousBatchingScheduler(
            _engine(model, params_v2, slots=2), max_queue=1, clock=clk)
        ab = sv.ShadowABScheduler(primary, shadow,
                                  sv.ABConfig(fraction=1.0, seed=0))
        wl = _workload(n=6, burst=6)             # one burst: floods queue
        out = sv.LoadGenerator(ab, wl, step_time_s=0.05).run()
        assert out.rejected == []                # incumbent absorbed all
        assert len(out.results) == 6
        assert ab.mirror_shed > 0                # shadow queue overflowed
        assert set(out.results) == {r.rid for r in wl.requests}
        assert not any(r.startswith("shadow:") for r in out.results)

    def test_facade_rejects_mismatched_construction(self, model,
                                                    params):
        clk = sv.VirtualClock()
        a = _sched(_engine(model, params), clk)
        b = _sched(_engine(model, params), sv.VirtualClock())
        with pytest.raises(ValueError):          # clocks must be shared
            sv.ShadowABScheduler(a, b, sv.ABConfig())
        with pytest.raises(ValueError):          # distinct schedulers
            sv.ShadowABScheduler(a, a, sv.ABConfig())
        with pytest.raises(ValueError):
            sv.ABConfig(fraction=2.0)
        with pytest.raises(ValueError):
            sv.ABConfig(mirror_prefix="")


# ---------------------------------------------------------------------------
# observability wiring + default-off identity (the house rules)
# ---------------------------------------------------------------------------


class TestObservability:
    def test_loaded_and_swapped_events_feed_metrics(self, model, params,
                                                    tmp_path):
        _save_versions(tmp_path, params, 100, 200)
        restore0 = obs_bridge.SERVING_RELOAD_DURATION.count(
            phase="restore")
        boot, _ = sv.load_serving_params(
            str(tmp_path), {"params": params}, params_key="params",
            step=100)
        assert obs_bridge.SERVING_WEIGHTS_STEP.value() == 100
        assert obs_bridge.SERVING_RELOAD_DURATION.count(
            phase="restore") == restore0 + 1
        eng = _engine(model, boot, slots=2)
        rl = sv.HotReloader(_sched(eng), str(tmp_path),
                            like={"params": params},
                            params_key="params", current_step=100)
        val0 = obs_bridge.SERVING_RELOAD_DURATION.count(phase="validate")
        swap0 = obs_bridge.SERVING_RELOAD_DURATION.count(phase="swap")
        assert rl.reload(step=200).ok
        assert obs_bridge.SERVING_WEIGHTS_STEP.value() == 200
        assert obs_bridge.SERVING_RELOAD_DURATION.count(
            phase="validate") == val0 + 1
        assert obs_bridge.SERVING_RELOAD_DURATION.count(
            phase="swap") == swap0 + 1
        rl.rollback()                            # swap only, no phases
        assert obs_bridge.SERVING_WEIGHTS_STEP.value() == 100
        assert obs_bridge.SERVING_RELOAD_DURATION.count(
            phase="validate") == val0 + 1
        assert obs_bridge.SERVING_RELOAD_DURATION.count(
            phase="swap") == swap0 + 2

    def test_default_off_byte_identity(self, model, params):
        """A scheduler with nothing reload-shaped constructed behaves
        byte-for-byte as before: zero reload events, reload metrics
        untouched, weights_version pinned at 0, and identical reruns
        emit identical event streams and token streams."""
        step0 = obs_bridge.SERVING_WEIGHTS_STEP.value()
        hist0 = sum(obs_bridge.SERVING_RELOAD_DURATION.count(phase=p)
                    for p in ("restore", "validate", "swap"))

        def run():
            eng = _engine(model, params)
            sched = _sched(eng)
            with _EventTap() as tap:
                out = sv.LoadGenerator(sched, _workload(),
                                       step_time_s=0.05).run()
            return eng, tap.events, out

        eng1, ev1, out1 = run()
        eng2, ev2, out2 = run()
        assert eng1.weights_version == 0
        for kind in ("serving_weights_loaded", "serving_weights_swapped",
                     "serving_reload_failed"):
            assert [e for e in ev1 if e.get("event") == kind] == []
        # identical reruns: identical event streams (modulo wall-clock
        # measurement fields) and identical tokens — the determinism
        # default-off rides on
        def scrub(events):
            drop = ("time", "duration_s", "dispatch_s", "restore_s")
            return [{k: v for k, v in e.items() if k not in drop}
                    for e in events]

        assert scrub(ev1) == scrub(ev2)
        assert {k: v.tokens for k, v in out1.results.items()} == \
               {k: v.tokens for k, v in out2.results.items()}
        assert obs_bridge.SERVING_WEIGHTS_STEP.value() == step0
        assert sum(obs_bridge.SERVING_RELOAD_DURATION.count(phase=p)
                   for p in ("restore", "validate", "swap")) == hist0

    def test_chain_hooks_compose_and_default_off(self):
        calls = []
        h = sv.chain_hooks(
            lambda s, sch: calls.append(("a", s)),
            None,
            lambda s, sch: calls.append(("b", s)))
        h(3, None)
        assert calls == [("a", 3), ("b", 3)]
        assert sv.chain_hooks() is None
        assert sv.chain_hooks(None, None) is None
