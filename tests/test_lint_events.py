"""Event-coverage lint (ISSUE 13 satellite): every ``emit_event`` kind
under ``apex_tpu/`` is either bridged to a metric handler or explicitly
allowlisted as countable-only — a typo'd kind can no longer drop its
measurements silently (the bridge ignores unknown kinds by design).

The repo-level check runs the real tree; the unit tests pin the lint's
own behavior on synthetic sources (unknown kind, non-literal kind, dead
handler, stale allowlist both ways).
"""

import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

from check_events import (  # noqa: E402
    ALLOWLIST,
    check,
    collect_emits_from_source,
    collect_handlers,
    find_violations,
)


def test_repo_events_are_clean():
    assert find_violations() == []


def test_cli_exit_code_clean():
    tool = Path(__file__).resolve().parent.parent / "tools" / \
        "check_events.py"
    proc = subprocess.run([sys.executable, str(tool)],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "events lint clean" in proc.stdout


def _emits(src):
    return collect_emits_from_source(src, "fake.py")


def test_unknown_kind_flagged():
    emits = _emits('emit_event("totally_new_kind", x=1)\n')
    problems = check(emits, handlers=[], allowlist=frozenset())
    assert len(problems) == 1
    assert "totally_new_kind" in problems[0]
    assert "silently drop" in problems[0]


def test_handled_and_allowlisted_kinds_pass():
    emits = _emits('emit_event("a", x=1)\nemit_event("b")\n')
    assert check(emits, handlers=["a"], allowlist=frozenset({"b"})) == []


def test_non_literal_kind_flagged():
    emits = _emits('kind = "x"\nemit_event(kind, x=1)\n')
    problems = check(emits, handlers=[], allowlist=frozenset())
    assert len(problems) == 1
    assert "string literals" in problems[0]


def test_dead_handler_flagged():
    problems = check(_emits('emit_event("a")\n'),
                     handlers=["a", "ghost"], allowlist=frozenset())
    assert len(problems) == 1
    assert "ghost" in problems[0] and "dead handler" in problems[0]


def test_stale_allowlist_flagged_both_ways():
    # entry that is also handled
    problems = check(_emits('emit_event("a")\n'), handlers=["a"],
                     allowlist=frozenset({"a"}))
    assert len(problems) == 1 and "also handled" in problems[0]
    # entry nothing emits
    problems = check(_emits('emit_event("a")\n'), handlers=["a"],
                     allowlist=frozenset({"never_emitted"}))
    assert len(problems) == 1 and "emitted nowhere" in problems[0]


def test_multiline_and_attribute_calls_collected():
    src = ('from apex_tpu._logging import emit_event\n'
           'import apex_tpu._logging as lg\n'
           'emit_event(\n    "wrapped_kind",\n    a=1)\n'
           'lg.emit_event("attr_kind")\n')
    kinds = {e.kind for e in _emits(src)}
    assert kinds == {"wrapped_kind", "attr_kind"}


def test_bridge_handlers_parse_and_cover_serving_control_plane():
    bridge = Path(__file__).resolve().parent.parent / "apex_tpu" / \
        "obs" / "bridge.py"
    handlers = set(collect_handlers(bridge.read_text()))
    # the control-plane counters this PR added must stay bridged (and
    # therefore OUT of the allowlist)
    for kind in ("serving_request_preempted", "serving_request_cancelled",
                 "serving_request_shed"):
        assert kind in handlers
        assert kind not in ALLOWLIST
