"""Training-supervisor subsystem tests (ISSUE 2 tentpole).

Every host-loop hardening path runs deterministically on CPU: the step
watchdog (synchronous deadline + monitor thread + heartbeat file),
classified transient retry with deterministic jitter, the validating
data-pipeline guard with its bounded skip budget, the supervisor-domain
fault injectors, and the escalation policy — ending with THE acceptance
run: flaky iterator + corrupt batch + injected slow step under a
deadline → retries, skips within budget, watchdog fires, emergency
checkpoint written and validated, restart resumes bit-identically.  No
real sleep here exceeds ~1 s.
"""

import json
import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import resilience as rz
from apex_tpu._logging import _RANK_INFO_WARNED, _debug_once, emit_event
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer.pipeline_parallel._timers import Timers


class FakeClock:
    """Injectable monotonic clock — deadline logic without real waits."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def events():
    """Capture structured apex_tpu.events as parsed dicts.

    Returns ``get(kind=None)`` — all events, or just one kind.
    """
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    logger = logging.getLogger("apex_tpu.events")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)

    def get(kind=None):
        parsed = [json.loads(r) for r in records]
        return parsed if kind is None else [e for e in parsed
                                            if e["event"] == kind]

    yield get
    logger.removeHandler(handler)


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y)


# --------------------------------------------------------------------------
# _logging satellites: monotonic duration_s + debug-once rank-info failures
# --------------------------------------------------------------------------

class TestLoggingSatellites:
    def test_emit_event_t0_adds_monotonic_duration(self):
        t0 = time.monotonic()
        ev = emit_event("unit_timing_event", t0=t0, detail=1)
        assert ev["duration_s"] >= 0.0
        assert ev["detail"] == 1

    def test_emit_event_without_t0_has_no_duration(self):
        assert "duration_s" not in emit_event("unit_plain_event")

    def test_rank_info_failures_log_once_at_debug(self):
        records = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r)
        logger = logging.getLogger("apex_tpu._logging")
        logger.addHandler(handler)
        logger.setLevel(logging.DEBUG)
        try:
            _RANK_INFO_WARNED.discard("unit_test_key")
            _debug_once("unit_test_key", "unit thing", ValueError("boom"))
            _debug_once("unit_test_key", "unit thing", ValueError("boom"))
        finally:
            logger.removeHandler(handler)
        assert len(records) == 1
        assert records[0].levelno == logging.DEBUG
        assert "boom" in records[0].getMessage()


# --------------------------------------------------------------------------
# retry: classification, deterministic jitter, events
# --------------------------------------------------------------------------

class TestRetry:
    def test_transient_retries_then_recovers(self, events):
        calls, slept = [], []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("blip")
            return "ok"

        policy = rz.RetryPolicy(max_attempts=4, base_delay_s=0.25)
        assert rz.retry_transient(fn, policy=policy, what="op",
                                  sleep=slept.append) == "ok"
        assert len(calls) == 3
        assert slept == [policy.delay_s("op", 1), policy.delay_s("op", 2)]
        assert slept[1] > slept[0]  # exponential backoff
        assert len(events("retry_attempt")) == 2
        [rec] = events("retry_recovered")
        assert rec["attempts"] == 3 and rec["duration_s"] >= 0.0

    def test_non_transient_propagates_first_attempt(self, events):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("deterministic bug")

        with pytest.raises(ValueError):
            rz.retry_transient(fn, sleep=lambda s: None)
        assert len(calls) == 1
        assert events() == []

    def test_stop_iteration_propagates_untouched(self):
        it = iter([])
        with pytest.raises(StopIteration):
            rz.retry_transient(lambda: next(it), sleep=lambda s: None)

    def test_exhaustion_raises_retry_exhausted(self, events):
        def fn():
            raise ConnectionError("down")

        policy = rz.RetryPolicy(max_attempts=3, base_delay_s=0.0)
        with pytest.raises(rz.RetryExhausted) as ei:
            rz.retry_transient(fn, policy=policy, what="op",
                               sleep=lambda s: None)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last, ConnectionError)
        assert isinstance(ei.value.__cause__, ConnectionError)
        [ex] = events("retry_exhausted")
        assert ex["attempts"] == 3 and "down" in ex["error"]

    def test_marker_classification_catches_status_anchored_errors(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise RuntimeError("UNAVAILABLE: tunnel reset")
            return 1

        assert rz.retry_transient(fn, sleep=lambda s: None) == 1
        assert len(calls) == 2
        # lowercase words in deterministic failure text do NOT match
        with pytest.raises(RuntimeError, match="internal"):
            rz.retry_transient(
                lambda: (_ for _ in ()).throw(
                    RuntimeError("lowering failed: internal op")),
                sleep=lambda s: None)

    def test_jitter_is_deterministic_and_seed_decorrelated(self):
        p = rz.RetryPolicy(seed=0)
        assert p.delay_s("save", 1) == p.delay_s("save", 1)
        assert p.delay_s("save", 1) != p.delay_s("fetch", 1)
        assert rz.RetryPolicy(seed=1).delay_s("save", 1) != \
            p.delay_s("save", 1)
        # delays are bounded by max_delay_s even with jitter
        cap = rz.RetryPolicy(base_delay_s=1.0, max_delay_s=1.5, jitter=10.0)
        assert cap.delay_s("x", 5) <= 1.5

    def test_degenerate_policies_rejected(self):
        with pytest.raises(ValueError):
            rz.RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            rz.RetryPolicy(backoff=0.5)
        with pytest.raises(ValueError):
            rz.RetryPolicy(jitter=-1.0)

    def test_transient_error_marker_class_is_retried(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise rz.TransientError("caller-classified")
            return "ok"

        assert rz.retry_transient(fn, sleep=lambda s: None) == "ok"


# --------------------------------------------------------------------------
# timers snapshot (watchdog diagnostics source)
# --------------------------------------------------------------------------

class TestTimersSnapshot:
    def test_snapshot_is_non_destructive_and_includes_inflight(self):
        timers = Timers()
        timers("fwd").start()
        time.sleep(0.02)
        snap = timers.snapshot()
        assert snap["fwd"]["running"] is True
        assert snap["fwd"]["total_s"] > 0.0
        # unlike elapsed(), nothing was stopped or reset
        assert timers("fwd").running is True
        timers("fwd").stop()
        total = timers.snapshot()["fwd"]["total_s"]
        assert timers.snapshot()["fwd"]["total_s"] == total  # idempotent

    def test_snapshot_mid_start_does_not_pair_stale_t0(self, monkeypatch):
        """A snapshot landing inside start() — the widest monitor-thread
        race window — must never combine running=True with the PREVIOUS
        region's _t0 (which would inflate total_s by the whole idle gap
        between regions)."""
        from apex_tpu.transformer.pipeline_parallel import _timers as T

        timers = Timers()
        t = timers("fwd")
        t.start()
        t.stop()  # region 1 done; its end stamp lingers in _t0
        fake_now = time.perf_counter() + 100.0  # pretend a 100 s idle gap
        state = {"snap": None}

        def counter():
            if state["snap"] is None:
                # emulate the monitor sampling at the exact instant
                # start() reads the clock (recurses into this counter,
                # guarded by the snap-is-set flag)
                state["snap"] = {}
                state["snap"] = timers.snapshot()["fwd"]
            return fake_now

        monkeypatch.setattr(T.time, "perf_counter", counter)
        t.start()
        assert state["snap"]["total_s"] < 1.0  # region 1 only, not the gap


# --------------------------------------------------------------------------
# step watchdog + heartbeat
# --------------------------------------------------------------------------

class TestWatchdog:
    def test_fast_step_passes(self):
        wd = rz.StepWatchdog(1.0, clock=FakeClock())
        wd.arm(0)
        wd.disarm()  # no raise

    def test_slow_step_raises_with_diagnostics(self, events):
        clock = FakeClock()
        wd = rz.StepWatchdog(1.0, clock=clock)
        wd.beat(4)
        clock.advance(0.5)
        wd.arm(5)
        clock.advance(2.5)
        with pytest.raises(rz.StepDeadlineExceeded) as ei:
            wd.disarm()
        e = ei.value
        assert e.step == 5 and e.elapsed_s == pytest.approx(2.5)
        assert e.diagnostics["heartbeat_age_s"] == pytest.approx(3.0)
        assert isinstance(e.diagnostics["live_arrays"], int)
        [stall] = events("watchdog_stall")
        assert stall["step"] == 5

    def test_timers_snapshot_rides_the_stall_dump(self):
        clock = FakeClock()
        timers = Timers()
        timers("fwd").start()
        wd = rz.StepWatchdog(1.0, timers=timers, clock=clock)
        wd.arm(0)
        clock.advance(5.0)
        with pytest.raises(rz.StepDeadlineExceeded) as ei:
            wd.disarm()
        assert ei.value.diagnostics["timers"]["fwd"]["running"] is True
        timers("fwd").stop()

    def test_monitor_thread_reports_mid_stall(self, events, tmp_path):
        """A hung step leaves evidence BEFORE it ends: the monitor dumps
        the stall event and marks the heartbeat while still armed."""
        hb = str(tmp_path / "heartbeat.json")
        wd = rz.StepWatchdog(0.05, heartbeat_path=hb, poll_interval_s=0.01)
        with wd:
            wd.arm(7)
            deadline = time.monotonic() + 2.0
            while not events("watchdog_stall") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(events("watchdog_stall")) == 1
            assert rz.read_heartbeat(hb)["stalled"] is True
            with pytest.raises(rz.StepDeadlineExceeded):
                wd.disarm()
        # one report per armed step: disarm did not re-emit
        assert len(events("watchdog_stall")) == 1

    def test_step_context_does_not_double_fire_on_body_error(self):
        clock = FakeClock()
        wd = rz.StepWatchdog(0.1, clock=clock)
        with pytest.raises(ValueError, match="body bug"):
            with wd.step(0):
                clock.advance(99.0)  # deadline long blown...
                raise ValueError("body bug")  # ...but the body's error wins
        wd.arm(1)  # armed state was cleaned up
        wd.disarm()

    def test_disarm_without_arm_is_a_usage_error(self):
        with pytest.raises(RuntimeError, match="without a matching arm"):
            rz.StepWatchdog(1.0).disarm()

    def test_degenerate_deadline_rejected(self):
        with pytest.raises(ValueError):
            rz.StepWatchdog(0.0)

    def test_heartbeat_roundtrip_and_atomicity(self, tmp_path):
        hb = str(tmp_path / "hb.json")
        payload = rz.write_heartbeat(hb, 42, ckpt_path="/ckpts/step_42")
        got = rz.read_heartbeat(hb)
        assert got["step"] == 42
        assert got["ckpt_path"] == "/ckpts/step_42"
        assert got["pid"] == os.getpid()
        assert got["monotonic"] == payload["monotonic"]
        # no temp litter: the write is temp + atomic rename
        assert os.listdir(tmp_path) == ["hb.json"]

    def test_concurrent_heartbeat_writers_never_tear_the_file(self, tmp_path):
        """The monitor thread (stall marker) and the main thread (beat)
        share a pid and can write simultaneously — every read must still
        parse (per-thread temp names keep os.replace atomic)."""
        hb = str(tmp_path / "hb.json")
        rz.write_heartbeat(hb, 0)
        stop = threading.Event()
        errors = []

        def hammer(tid):
            i = 0
            try:
                while not stop.is_set():
                    rz.write_heartbeat(hb, i, ckpt_path=f"/ckpts/{tid}/{i}")
                    i += 1
            except Exception as e:  # surfaced below, not swallowed
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(k,))
                   for k in range(2)]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 0.3
        try:
            while time.monotonic() < deadline:
                got = rz.read_heartbeat(hb)  # JSONDecodeError == torn write
                assert got["pid"] == os.getpid()
        finally:
            stop.set()
            for th in threads:
                th.join()
        assert not errors

    def test_beat_failure_never_kills_the_run(self, tmp_path):
        wd = rz.StepWatchdog(
            1.0, heartbeat_path=str(tmp_path / "no_such_dir" / "hb.json"))
        wd.beat(0)  # logged, not raised

    def test_beat_keeps_newest_ckpt_path_between_saves(self, tmp_path):
        # with checkpoint_every > 1 most beats carry no ckpt_path — the
        # heartbeat's resume pointer must survive them, not be nulled
        hb = str(tmp_path / "hb.json")
        wd = rz.StepWatchdog(1.0, heartbeat_path=hb)
        wd.beat(99, ckpt_path="/ckpts/step_99")
        wd.beat(100)
        got = rz.read_heartbeat(hb)
        assert got["step"] == 100
        assert got["ckpt_path"] == "/ckpts/step_99"
        wd.beat(199, ckpt_path="/ckpts/step_199")
        assert rz.read_heartbeat(hb)["ckpt_path"] == "/ckpts/step_199"

    def test_heartbeat_carries_rank_info_when_model_parallel(
            self, tmp_path, devices):
        """ISSUE 3 satellite: with model parallelism initialized, the
        heartbeat names WHICH slice member wrote it (rank descriptor +
        machine-readable mesh shape); without it, neither key appears."""
        from apex_tpu.transformer import parallel_state

        hb = str(tmp_path / "hb.json")
        rz.write_heartbeat(hb, 1)
        got = rz.read_heartbeat(hb)
        assert "rank_info" not in got and "mesh" not in got

        parallel_state.initialize_model_parallel(2, devices=devices[:8])
        try:
            rz.write_heartbeat(hb, 2)
        finally:
            parallel_state.destroy_model_parallel()
        got = rz.read_heartbeat(hb)
        assert got["mesh"] == {"dp": 4, "pp": 1, "tp": 2}
        assert "dp=4" in got["rank_info"] and "tp=2" in got["rank_info"]


# --------------------------------------------------------------------------
# data-pipeline guard
# --------------------------------------------------------------------------

def _clean_batch(i=0):
    return {"x": np.full((2, 3), float(i), np.float32),
            "y": np.arange(2, dtype=np.int32)}


class TestDataGuard:
    def test_clean_batches_pass_untouched(self):
        batches = [_clean_batch(i) for i in range(3)]
        g = rz.GuardedIterator(iter(batches),
                               spec=rz.spec_of(_clean_batch()))
        out = list(g)
        assert len(out) == 3 and g.skipped == 0 and g.delivered == 3
        assert out[1] is batches[1]

    @pytest.mark.parametrize("mutate,reason_word", [
        (lambda b: {**b, "x": np.full((2, 3), np.nan, np.float32)},
         "non-finite"),
        (lambda b: {**b, "x": b["x"][1:]}, "shape"),
        (lambda b: {**b, "x": b["x"].astype(np.float64)}, "dtype"),
    ])
    def test_corrupt_batch_skipped_with_reason(self, events, mutate,
                                               reason_word):
        bad = mutate(_clean_batch())
        g = rz.GuardedIterator(iter([_clean_batch(0), bad, _clean_batch(2)]),
                               spec=rz.spec_of(_clean_batch()))
        out = list(g)
        assert len(out) == 2 and g.skipped == 1
        [skip] = events("batch_skipped")
        assert reason_word in skip["reasons"][0]
        assert "'x'" in skip["reasons"][0]  # the leaf is named

    def test_structure_mismatch_skipped(self):
        g = rz.GuardedIterator(iter([{"z": np.zeros((2, 3), np.float32)}]),
                               spec=rz.spec_of(_clean_batch()),
                               skip_budget=1)
        with pytest.raises(StopIteration):
            next(g)
        assert g.skipped == 1

    def test_skip_budget_exceeded_raises(self):
        bads = [{**_clean_batch(), "x": np.full((2, 3), np.nan, np.float32)}
                for _ in range(3)]
        g = rz.GuardedIterator(iter(bads), spec=rz.spec_of(_clean_batch()),
                               skip_budget=1)
        with pytest.raises(rz.SkipBudgetExceeded) as ei:
            next(g)
        assert ei.value.skipped == 2 and ei.value.budget == 1

    def test_stall_timeout_raises(self, events):
        clock = FakeClock()

        def slow_source():
            clock.advance(5.0)  # the fetch itself "takes" 5 s
            yield _clean_batch()

        g = rz.GuardedIterator(slow_source(), stall_timeout_s=1.0,
                               clock=clock)
        with pytest.raises(rz.DataStallError):
            next(g)
        [ev] = events("data_stall")
        assert ev["fetch_s"] == pytest.approx(5.0)

    def test_stalled_batch_is_redelivered_not_lost(self):
        """The stall raise happens AFTER the producer delivered — the
        late batch must come back on the next call, or a chronically
        slow producer silently loses data with no budget accounting."""
        clock = FakeClock()

        def source():
            for i in range(3):
                clock.advance(5.0 if i == 1 else 0.0)
                yield _clean_batch(i)

        g = rz.GuardedIterator(source(), stall_timeout_s=1.0, clock=clock)
        _tree_equal(next(g), _clean_batch(0))
        with pytest.raises(rz.DataStallError):
            next(g)
        _tree_equal(next(g), _clean_batch(1))  # the late batch, redelivered
        _tree_equal(next(g), _clean_batch(2))
        assert g.delivered == 3 and g.skipped == 0

    def test_spec_locks_to_first_batch_when_omitted(self):
        g = rz.GuardedIterator(iter([_clean_batch(0), _clean_batch(1),
                                     {**_clean_batch(),
                                      "x": np.zeros((9, 9), np.float32)}]))
        assert next(g) is not None
        assert next(g) is not None
        with pytest.raises(StopIteration):  # third batch violates the spec
            next(g)
        assert g.skipped == 1

    def test_check_finite_false_admits_nan(self):
        bad = {**_clean_batch(), "x": np.full((2, 3), np.nan, np.float32)}
        g = rz.GuardedIterator(iter([bad]), spec=rz.spec_of(_clean_batch()),
                               check_finite=False)
        assert np.isnan(next(g)["x"]).all()

    def test_degenerate_arguments_rejected(self):
        with pytest.raises(ValueError):
            rz.GuardedIterator(iter([]), skip_budget=-1)
        with pytest.raises(ValueError):
            rz.GuardedIterator(iter([]), stall_timeout_s=0.0)


# --------------------------------------------------------------------------
# supervisor-domain fault injection
# --------------------------------------------------------------------------

class TestSupervisorFaults:
    def test_slow_step_stalls_only_configured_steps(self):
        slept = []
        slow = rz.SlowStep((3,), 0.7, sleep=slept.append)
        for i in range(5):
            slow(i)
        assert slept == [0.7]

    def test_flaky_iterator_fails_n_then_succeeds_without_consuming(self):
        fl = rz.FlakyIterator(iter([10, 11, 12]), fail_at=(1,), failures=2,
                              exc_type=ConnectionError)
        got, failures = [], 0
        while True:
            try:
                got.append(next(fl))
            except ConnectionError:
                failures += 1
            except StopIteration:
                break
        assert got == [10, 11, 12]  # nothing lost, nothing reordered
        assert failures == 2

    def test_corrupt_batch_inserts_copy_preserving_clean_stream(self):
        clean = [{"x": np.full((3, 2), float(i), np.float32)}
                 for i in range(4)]
        cb = rz.CorruptBatch(iter(clean), at=(2,), mode="nan", seed=5)
        out = list(cb)
        assert len(out) == 5  # one inserted corrupt copy
        assert np.isnan(out[2]["x"]).any()  # the insert, at clean index 2
        # the clean stream is intact and untouched
        for got, want in zip([out[0], out[1], out[3], out[4]], clean):
            np.testing.assert_array_equal(got["x"], np.asarray(want["x"]))

    def test_corrupt_batch_modes_are_guard_detectable(self):
        spec = rz.spec_of({"x": np.zeros((3, 2), np.float32)})
        for mode in ("nan", "shape", "dtype"):
            cb = rz.CorruptBatch(
                iter([{"x": np.zeros((3, 2), np.float32)}]), at=(0,),
                mode=mode)
            corrupted = next(cb)
            assert rz.validate_batch(corrupted, spec), mode

    def test_corrupt_batch_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="mode"):
            rz.CorruptBatch(iter([]), mode="gamma-ray")

    def test_corrupt_batch_raises_when_nothing_to_corrupt(self):
        # nan mode needs a floating leaf; an int-only batch is a plan
        # mismatch, not a silent clean-copy insert that desyncs the stream
        cb = rz.CorruptBatch(
            iter([{"y": np.zeros((2,), np.int32)}]), at=(0,), mode="nan")
        with pytest.raises(ValueError, match="no floating-point"):
            next(cb)


# --------------------------------------------------------------------------
# checkpoint-manager retry wiring
# --------------------------------------------------------------------------

class TestCheckpointManagerRetry:
    def test_save_retries_transient_io(self, tmp_path, monkeypatch, events):
        from apex_tpu.resilience import checkpoint as ckpt

        real = ckpt.save_checkpoint
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) < 3:
                raise OSError("disk blip")
            return real(*a, **kw)

        monkeypatch.setattr(ckpt, "save_checkpoint", flaky)
        mgr = rz.CheckpointManager(
            str(tmp_path), retry=rz.RetryPolicy(base_delay_s=0.001))
        path = mgr.save(0, {"a": jnp.ones((2,))})
        rz.validate_checkpoint(path)
        assert len(calls) == 3
        assert len(events("retry_attempt")) == 2

    def test_restore_does_not_retry_checkpoint_errors(self, tmp_path,
                                                      monkeypatch):
        from apex_tpu.resilience import checkpoint as ckpt

        calls = []
        real = ckpt.restore_checkpoint

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(ckpt, "restore_checkpoint", counting)
        mgr = rz.CheckpointManager(
            str(tmp_path), retry=rz.RetryPolicy(base_delay_s=0.001))
        with pytest.raises(rz.CheckpointError):  # deterministic: no retry
            mgr.restore(like={"a": jnp.ones((2,))})
        assert len(calls) == 1

    def test_no_policy_means_no_wrapping(self, tmp_path):
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, {"a": jnp.ones((2,))})
        restored, step = mgr.restore(like={"a": jnp.zeros((2,))})
        assert step == 0

    def test_restore_retries_transient_read_blip_from_newest(
            self, tmp_path, monkeypatch, events):
        # an OSError mid-read of a perfectly good newest checkpoint must
        # engage the retry, not be wrapped into CheckpointError and make
        # the fallback walk silently resume an OLDER step
        from apex_tpu.resilience import checkpoint as ckpt

        mgr = rz.CheckpointManager(
            str(tmp_path), retry=rz.RetryPolicy(base_delay_s=0.001))
        mgr.save(0, {"a": jnp.zeros((2,))})
        mgr.save(1, {"a": jnp.ones((2,))})

        real = ckpt._read_record
        calls = []

        def blips_once(*a, **kw):
            calls.append(1)
            if len(calls) == 1:
                raise OSError("Connection reset by peer")
            return real(*a, **kw)

        monkeypatch.setattr(ckpt, "_read_record", blips_once)
        restored, step = mgr.restore(like={"a": jnp.zeros((2,))})
        assert step == 1  # newest, not the pre-blip fallback
        assert len(events("retry_attempt")) == 1
        assert events("checkpoint_rejected") == []

    def test_unreadable_newest_manifest_still_falls_back(self, tmp_path):
        # a deterministic OSError on the manifest PROBE (not mid-payload)
        # rejects the candidate: the walk must reach the older valid step
        mgr = rz.CheckpointManager(str(tmp_path))
        mgr.save(0, {"a": jnp.zeros((2,))})
        p1 = mgr.save(1, {"a": jnp.ones((2,))})
        manifest = os.path.join(p1, "manifest.json")
        os.remove(manifest)
        os.mkdir(manifest)  # open() -> IsADirectoryError, not FileNotFound
        restored, step = mgr.restore(like={"a": jnp.zeros((2,))})
        assert step == 0

    def test_marker_text_inside_checkpoint_error_is_not_transient(self):
        from apex_tpu.resilience.retry import is_transient

        e = rz.CheckpointError(
            "no valid checkpoint under '/ckpts'; rejected: "
            '["OSError: [Errno 104] Connection reset by peer"]')
        assert not is_transient(e, rz.RetryPolicy())


# --------------------------------------------------------------------------
# escalation policy
# --------------------------------------------------------------------------

def _fast_config(**kw):
    kw.setdefault("step_deadline_s", 30.0)
    kw.setdefault("poll_interval_s", 5.0)
    kw.setdefault("retry", rz.RetryPolicy(max_attempts=3, base_delay_s=0.0))
    return rz.SupervisorConfig(**kw)


class TestEscalation:
    def test_failures_below_threshold_do_not_abort(self):
        sup = rz.TrainingSupervisor(
            None, _fast_config(max_consecutive_failures=3))
        sup.record_failure(0, {}, OSError("x"))
        sup.record_failure(1, {}, OSError("x"))
        assert sup.consecutive_failures == 2
        sup.record_success()
        assert sup.consecutive_failures == 0

    def test_threshold_escalates_with_validated_checkpoint(self, tmp_path,
                                                           events):
        mgr = rz.CheckpointManager(str(tmp_path))
        sup = rz.TrainingSupervisor(
            mgr, _fast_config(max_consecutive_failures=1))
        state = {"w": jnp.arange(4.0)}
        with pytest.raises(rz.TrainingAborted) as ei:
            sup.record_failure(9, state, rz.StepDeadlineExceeded(9, 1.0, 2.0))
        ab = ei.value
        assert ab.step == 9 and ab.checkpoint_path is not None
        rz.validate_checkpoint(ab.checkpoint_path)
        restored, step = mgr.restore(like={"w": jnp.zeros(4)})
        assert step == 9
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(4.0))
        [abort] = events("supervisor_abort")
        assert abort["checkpoint"] == ab.checkpoint_path
        assert abort["checkpoint_error"] is None

    def test_abort_survives_unwritable_checkpoint(self, events):
        mgr = rz.CheckpointManager("/proc/definitely/not/writable")
        sup = rz.TrainingSupervisor(
            mgr, _fast_config(max_consecutive_failures=1), sleep=lambda s: None)
        with pytest.raises(rz.TrainingAborted) as ei:
            sup.record_failure(3, {"w": jnp.ones(2)}, OSError("x"))
        assert ei.value.checkpoint_path is None
        [abort] = events("supervisor_abort")
        assert abort["checkpoint_error"] is not None

    def test_degenerate_config_rejected(self):
        with pytest.raises(ValueError):
            rz.SupervisorConfig(max_consecutive_failures=0)
        with pytest.raises(ValueError):
            rz.SupervisorConfig(checkpoint_every=0)
        with pytest.raises(ValueError):
            rz.SupervisorConfig(step_deadline_s=-1.0)


class TestSupervisedRun:
    def test_empty_iterator_completes_nothing(self):
        sup = rz.TrainingSupervisor(None, _fast_config())
        state, last = sup.run(lambda s, b, i: s, {"x": 0}, iter([]),
                              num_steps=5)
        assert last == -1 and state == {"x": 0}

    def test_flaky_fetch_is_recovered_without_failure_accounting(self):
        sup = rz.TrainingSupervisor(None, _fast_config(), sleep=lambda s: None)
        src = rz.FlakyIterator(iter(range(4)), fail_at=(1,), failures=2)
        seen = []
        state, last = sup.run(lambda s, b, i: seen.append((i, b)) or s,
                              None, src, num_steps=4)
        assert seen == [(0, 0), (1, 1), (2, 2), (3, 3)]
        assert last == 3 and sup.consecutive_failures == 0

    def test_unrelated_step_errors_propagate_unabsorbed(self):
        sup = rz.TrainingSupervisor(None, _fast_config())

        def bad_step(state, batch, step):
            raise ZeroDivisionError("model bug, not infrastructure")

        with pytest.raises(ZeroDivisionError):
            sup.run(bad_step, None, iter(range(3)), num_steps=3)

    def test_checkpoint_save_exhaustion_counts_as_failure(self, tmp_path,
                                                          monkeypatch):
        from apex_tpu.resilience import checkpoint as ckpt

        monkeypatch.setattr(
            ckpt, "save_checkpoint",
            lambda *a, **kw: (_ for _ in ()).throw(OSError("disk gone")))
        mgr = rz.CheckpointManager(str(tmp_path))
        sup = rz.TrainingSupervisor(
            mgr, _fast_config(max_consecutive_failures=1),
            sleep=lambda s: None)
        with pytest.raises(rz.TrainingAborted) as ei:
            sup.run(lambda s, b, i: s, {"x": jnp.ones(2)}, iter(range(3)),
                    num_steps=3)
        # the emergency checkpoint cannot be written either — abort still
        # happens, carrying no checkpoint path
        assert ei.value.checkpoint_path is None

    def test_fetch_failure_escalation_checkpoints_completed_step(
            self, tmp_path):
        """When a STEP's fetch fails, the state still predates that step
        — the emergency checkpoint must carry the completed step's label,
        or the documented resume (restored_step + 1) silently skips the
        step that never ran."""
        class OneGoodThenBroken:
            def __init__(self):
                self.n = 0

            def __iter__(self):
                return self

            def __next__(self):
                self.n += 1
                if self.n == 1:
                    return 1.0
                raise OSError("producer gone")

        mgr = rz.CheckpointManager(str(tmp_path))
        sup = rz.TrainingSupervisor(
            mgr, _fast_config(max_consecutive_failures=1, checkpoint_every=5),
            sleep=lambda s: None)
        with pytest.raises(rz.TrainingAborted) as ei:
            sup.run(lambda s, b, i: {"w": s["w"] + b}, {"w": jnp.zeros(2)},
                    OneGoodThenBroken(), num_steps=5)
        assert ei.value.step == 1  # the step whose fetch failed...
        restored, got = mgr.restore(like={"w": jnp.zeros(2)})
        assert got == 0  # ...but the checkpoint is the state AFTER step 0
        np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(2))
        # the resume recipe (got + 1) therefore re-attempts step 1

    def test_fetch_failure_before_any_step_checkpoints_initial_state(
            self, tmp_path):
        class Broken:
            def __iter__(self):
                return self

            def __next__(self):
                raise OSError("producer gone")

        mgr = rz.CheckpointManager(str(tmp_path))
        sup = rz.TrainingSupervisor(
            mgr, _fast_config(max_consecutive_failures=1, checkpoint_every=5),
            sleep=lambda s: None)
        with pytest.raises(rz.TrainingAborted):
            sup.run(lambda s, b, i: s, {"w": jnp.full(2, 7.0)}, Broken(),
                    num_steps=5)
        restored, got = mgr.restore(like={"w": jnp.zeros(2)})
        assert got == -1  # pre-first-step sentinel: resume starts at 0
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full(2, 7.0))

    def test_manager_retry_policy_is_not_nested(self, tmp_path, monkeypatch):
        # the documented recipe sets retry on BOTH the manager and the
        # supervisor config; the supervisor must defer to the manager's
        # loop, not multiply attempts to max_attempts**2 per save
        from apex_tpu.resilience import checkpoint as ckpt

        calls = []

        def failing_save(*a, **kw):
            calls.append(1)
            raise OSError("disk gone")

        monkeypatch.setattr(ckpt, "save_checkpoint", failing_save)
        mgr = rz.CheckpointManager(
            str(tmp_path),
            retry=rz.RetryPolicy(max_attempts=2, base_delay_s=0.0))
        sup = rz.TrainingSupervisor(
            mgr, _fast_config(max_consecutive_failures=1),
            sleep=lambda s: None)
        with pytest.raises(rz.TrainingAborted):
            sup.run(lambda s, b, i: s, {"x": jnp.ones(2)}, iter(range(3)),
                    num_steps=3)
        # 2 attempts for the periodic save + 2 for the emergency save —
        # the supervisor's own 3-attempt policy never wrapped either
        assert len(calls) == 4


# --------------------------------------------------------------------------
# THE acceptance run (ISSUE 2): flaky fetch + corrupt batch + slow step
# under a deadline -> retry, skip, watchdog, emergency checkpoint,
# bit-identical resume.  JAX_PLATFORMS=cpu; no sleep longer than ~1 s.
# --------------------------------------------------------------------------

N_STEPS = 10
FLAKY_AT = 2      # fetch index that fails transiently (twice)
CORRUPT_AT = 4    # clean index that gets a corrupted copy inserted
SLOW_AT = 6       # step that stalls past the deadline
DEADLINE_S = 0.2
SLOW_S = 0.6


def _build_update():
    params = {"w": jnp.full((6, 6), 0.3, jnp.float32),
              "b": jnp.zeros((6,), jnp.float32)}
    opt = FusedAdam(lr=5e-2)

    def loss_fn(p, batch):
        pred = jnp.tanh(batch @ p["w"]) + p["b"]
        return jnp.mean((pred - 1.0) ** 2)

    @jax.jit
    def update(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        new_p, new_o = opt.step(grads, state["params"], state["opt"])
        return {"params": new_p, "opt": new_o}, loss

    state = {"params": params, "opt": opt.init(params)}
    # pre-warm the compile OUTSIDE any watchdog window: compilation cost
    # is not step time, and the acceptance deadline is 200 ms
    update(state, jnp.zeros((4, 6), jnp.float32))
    return state, update


def _batches():
    key = jax.random.PRNGKey(0)
    return [jax.random.normal(jax.random.fold_in(key, i), (4, 6))
            for i in range(N_STEPS)]


def _make_step_fn(update, losses, slow=None):
    def step_fn(state, batch, step):
        if slow is not None:
            slow(step)
        new_state, loss = update(state, batch)
        losses[step] = float(loss)
        return new_state

    return step_fn


def test_acceptance_faulted_run_degrades_then_resumes_bit_identically(
        tmp_path, events):
    batches = _batches()

    # ---- reference: uninterrupted supervised run
    ref_losses = {}
    ref_state, update = _build_update()
    ref_mgr = rz.CheckpointManager(str(tmp_path / "ref"), keep=N_STEPS)
    ref_sup = rz.TrainingSupervisor(ref_mgr, _fast_config())
    ref_final, ref_last = ref_sup.run(
        _make_step_fn(update, ref_losses), ref_state, iter(batches),
        num_steps=N_STEPS)
    assert ref_last == N_STEPS - 1
    assert sorted(ref_losses) == list(range(N_STEPS))

    # ---- victim: flaky fetch + corrupt batch + slow step under deadline
    run_losses = {}
    init_state, update_b = _build_update()
    hb_path = str(tmp_path / "heartbeat.json")
    stream = rz.GuardedIterator(
        rz.CorruptBatch(
            rz.FlakyIterator(iter(batches), fail_at=(FLAKY_AT,), failures=2),
            at=(CORRUPT_AT,), mode="nan", seed=7),
        spec=rz.spec_of(batches[0]), skip_budget=2)
    cfg = rz.SupervisorConfig(
        step_deadline_s=DEADLINE_S, poll_interval_s=0.02,
        max_consecutive_failures=1, checkpoint_every=1,
        heartbeat_path=hb_path,
        retry=rz.RetryPolicy(max_attempts=4, base_delay_s=0.001,
                             max_delay_s=0.01))
    mgr = rz.CheckpointManager(str(tmp_path / "victim"), keep=3)
    sup = rz.TrainingSupervisor(mgr, cfg)
    with pytest.raises(rz.TrainingAborted) as ei:
        sup.run(_make_step_fn(update_b, run_losses, slow=rz.SlowStep(
            (SLOW_AT,), SLOW_S)), init_state, stream, num_steps=N_STEPS)
    aborted = ei.value

    # every recovery path fired, each exactly as planned:
    assert len(events("retry_attempt")) == 2          # flaky fetch, twice
    assert len(events("retry_recovered")) == 1
    assert stream.skipped == 1                        # corrupt copy dropped
    assert len(events("batch_skipped")) == 1
    assert len(events("watchdog_stall")) == 1         # the slow step
    assert len(events("supervisor_abort")) == 1
    # the slow step COMPLETED (late): its loss was computed and recorded
    assert sorted(run_losses) == list(range(SLOW_AT + 1))

    # graceful degradation: validated emergency checkpoint at the abort
    # step, recorded in the heartbeat for the external orchestrator
    assert aborted.step == SLOW_AT
    assert aborted.checkpoint_path is not None
    rz.validate_checkpoint(aborted.checkpoint_path)
    hb = rz.read_heartbeat(hb_path)
    assert hb["step"] == SLOW_AT
    assert hb["ckpt_path"] == aborted.checkpoint_path

    # ---- restart: resume from the emergency checkpoint, finish clean
    resume_template, update_c = _build_update()
    resumed, resume_step = mgr.restore(like=resume_template)
    assert resume_step == SLOW_AT
    sup2 = rz.TrainingSupervisor(mgr, _fast_config())
    final, last = sup2.run(
        _make_step_fn(update_c, run_losses), resumed,
        iter(batches[SLOW_AT + 1:]), num_steps=N_STEPS,
        start_step=SLOW_AT + 1)
    assert last == N_STEPS - 1

    # bit-identical to the uninterrupted reference: every recorded loss
    # and every leaf of the final state
    assert sorted(run_losses) == list(range(N_STEPS))
    for i in range(N_STEPS):
        assert run_losses[i] == ref_losses[i], (
            f"loss diverged at step {i}: {run_losses[i]} != {ref_losses[i]}")
    _tree_equal(final, ref_final)


# --------------------------------------------------------------------------
# asynchronous checkpoint pipeline under the supervisor (ISSUE 8):
# snapshot-only blocking, backpressure, failed-write ladder, emergency/
# shutdown joins, consistency veto — and THE acceptance run: an async-
# interrupted run resumes bit-identically through the existing harness
# --------------------------------------------------------------------------


def _accum_step(state, batch, step):
    return {"w": state["w"] + batch, "n": state["n"] + 1}


def _accum_state():
    return {"w": jnp.zeros((4, 4), jnp.float32), "n": jnp.int32(0)}


def _accum_batches(n):
    return [jnp.full((4, 4), float(i + 1), jnp.float32) for i in range(n)]


def _step_dirs(root):
    return sorted(d for d in os.listdir(root) if d.startswith("step_"))


def _dir_bytes(path):
    return {name: open(os.path.join(path, name), "rb").read()
            for name in sorted(os.listdir(path))}


class TestAsyncSupervisor:
    def test_async_run_matches_sync_run_byte_for_byte(self, tmp_path):
        """async_save=True must change WHEN the write happens, not one
        byte of what lands on disk — every periodic step dir compares
        equal to the sync run's, and the final states match."""
        roots = {"sync": str(tmp_path / "sync"),
                 "async": str(tmp_path / "async")}
        finals = {}
        for mode, root in roots.items():
            sup = rz.TrainingSupervisor(
                rz.CheckpointManager(root, keep=10),
                _fast_config(checkpoint_every=2,
                             async_save=(mode == "async")))
            finals[mode], last = sup.run(
                _accum_step, _accum_state(), _accum_batches(6), num_steps=6)
            assert last == 5
        _tree_equal(finals["sync"], finals["async"])
        assert _step_dirs(roots["sync"]) == _step_dirs(roots["async"])
        for d in _step_dirs(roots["sync"]):
            assert _dir_bytes(os.path.join(roots["sync"], d)) == \
                _dir_bytes(os.path.join(roots["async"], d)), d

    def test_heartbeat_pointer_advances_only_on_committed_dirs(
            self, tmp_path):
        hb = str(tmp_path / "hb.json")
        root = str(tmp_path / "ckpts")
        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(root, keep=10),
            _fast_config(checkpoint_every=1, async_save=True,
                         heartbeat_path=hb))
        sup.run(_accum_step, _accum_state(), _accum_batches(4), num_steps=4)
        beat = rz.read_heartbeat(hb)
        # the final drain published the LAST committed step's path
        assert beat["ckpt_path"] is not None
        assert beat["ckpt_path"].endswith("step_0000000003")
        rz.validate_checkpoint(beat["ckpt_path"])

    def test_failed_background_write_joins_failure_ladder(
            self, tmp_path, events):
        """A background write that exhausts its transient retries
        surfaces at the next step boundary as one supervisor failure —
        the same accounting a failed synchronous save gets."""
        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(str(tmp_path)),
            _fast_config(checkpoint_every=1, async_save=True,
                         max_consecutive_failures=50))
        # every write attempt dies on a transient error (hook runs per
        # record inside the write machinery, under config.retry)
        def bad_io(progress):
            raise OSError("injected transient write failure")

        sup._async.progress_hook = bad_io
        state, last = sup.run(_accum_step, _accum_state(),
                              _accum_batches(3), num_steps=3)
        assert last == 2  # the run survived: writes failed, steps didn't
        fails = events("supervisor_failure")
        assert fails and all(f["failure"] == "RetryExhausted"
                             for f in fails)
        assert not _step_dirs(str(tmp_path))

    def test_escalation_joins_inflight_write_then_checkpoints(
            self, tmp_path):
        """Emergency checkpointing must join the in-flight background
        write first (single-writer root) — both the periodic dir and the
        emergency dir end up committed and valid."""
        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(str(tmp_path), keep=10),
            _fast_config(checkpoint_every=1, async_save=True,
                         max_consecutive_failures=1))
        sup._async.progress_hook = lambda p: time.sleep(0.2)  # slow writer
        fut = sup._async.save(0, {"w": jnp.arange(4.0)})
        assert not fut.done()
        with pytest.raises(rz.TrainingAborted) as ei:
            sup.record_failure(1, {"w": jnp.ones(4)},
                               rz.StepDeadlineExceeded(1, 1.0, 2.0))
        # the join happened before the emergency save: the periodic
        # write committed (not swept/aborted), the emergency dir too
        assert fut.done() and fut.error is None
        assert _step_dirs(str(tmp_path)) == ["step_0000000000",
                                             "step_0000000001"]
        rz.validate_checkpoint(ei.value.checkpoint_path)

    def test_consistency_failure_vetoes_inflight_commit(
            self, tmp_path, events):
        """ISSUE 8: a failed consistency pass must ALSO veto the write
        already in the air — an untrusted lineage never becomes
        latest_valid_step, not even through a commit scheduled before
        the pass ran."""
        class FlakyConsistency:
            calls = 0

            def check(self, state, step):
                type(self).calls += 1
                if type(self).calls == 1:
                    raise rz.ReplicaDesyncError(step, [])
                return state

        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(str(tmp_path), keep=10),
            _fast_config(checkpoint_every=1, async_save=True,
                         consistency_check_interval=2,
                         max_consecutive_failures=50),
            consistency=FlakyConsistency())
        sup._async.progress_hook = lambda p: time.sleep(0.25)  # in flight
        state, last = sup.run(_accum_step, _accum_state(),
                              _accum_batches(6), num_steps=6)
        assert last == 5
        dirs = _step_dirs(str(tmp_path))
        # step 0's write was in flight when the step-1 pass failed: the
        # veto killed it.  Steps 1 and 2 never scheduled (untrusted);
        # the step-3 pass re-proved the state clean, so 3.. committed.
        assert "step_0000000000" not in dirs
        assert "step_0000000001" not in dirs
        assert "step_0000000002" not in dirs
        assert {"step_0000000003", "step_0000000004",
                "step_0000000005"} <= set(dirs)
        assert events("checkpoint_commit_vetoed")
        assert rz.latest_valid_step(str(tmp_path)) == 5

    def test_acceptance_async_interrupted_run_resumes_bit_identically(
            self, tmp_path):
        """THE ISSUE-8 acceptance run: preempt an async_save run mid-
        flight, restart from latest_valid_step through the normal
        restore path, finish — the final state is bit-identical to an
        uninterrupted SYNC run, and every surviving step dir is byte-
        identical to the sync run's."""
        n = 8
        sync_root = str(tmp_path / "sync")
        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(sync_root, keep=20),
            _fast_config(checkpoint_every=1))
        ref_final, _ = sup.run(_accum_step, _accum_state(),
                               _accum_batches(n), num_steps=n)

        async_root = str(tmp_path / "async")
        mgr = rz.CheckpointManager(async_root, keep=20)
        injector = rz.FaultInjector(rz.FaultPlan(preempt_steps=(5,)))

        def preempting_step(state, batch, step):
            injector.check_preemption(step)
            return _accum_step(state, batch, step)

        sup1 = rz.TrainingSupervisor(
            mgr, _fast_config(checkpoint_every=1, async_save=True))
        with pytest.raises(rz.SimulatedPreemption):
            sup1.run(preempting_step, _accum_state(), _accum_batches(n),
                     num_steps=n)
        # restart: newest VALID checkpoint (an in-flight write at the
        # kill either committed whole or is invisible), resume async
        resume_state, last = mgr.restore(like=_accum_state())
        assert last == rz.latest_valid_step(async_root) == 4
        sup2 = rz.TrainingSupervisor(
            mgr, _fast_config(checkpoint_every=1, async_save=True))
        final, done = sup2.run(_accum_step, resume_state,
                               _accum_batches(n)[last + 1:],
                               num_steps=n, start_step=last + 1)
        assert done == n - 1
        _tree_equal(final, ref_final)
        for d in _step_dirs(async_root):
            assert _dir_bytes(os.path.join(async_root, d)) == \
                _dir_bytes(os.path.join(sync_root, d)), d

    def test_resume_pointer_advances_under_sustained_backpressure(
            self, tmp_path):
        """Write duration persistently longer than the save interval:
        every success's future is consumed by the next save's
        backpressure join (poll never sees it), and the heartbeat's
        resume pointer must STILL advance mid-run — the lossless
        last_committed record, not future harvesting, feeds the beat."""
        hb = str(tmp_path / "hb.json")
        root = str(tmp_path / "ckpts")
        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(root, keep=20),
            _fast_config(checkpoint_every=1, async_save=True,
                         heartbeat_path=hb))
        sup._async.progress_hook = lambda p: time.sleep(0.1)  # slow write
        seen = {}

        def step_fn(state, batch, step):
            if step == 3:  # mid-run, while write(2) is still in the air
                seen["beat"] = rz.read_heartbeat(hb)
            return _accum_step(state, batch, step)

        sup.run(step_fn, _accum_state(), _accum_batches(4), num_steps=4)
        assert seen["beat"]["ckpt_path"] is not None, (
            "resume pointer never advanced while writes overlapped saves")
        rz.validate_checkpoint(seen["beat"]["ckpt_path"])
        assert len(_step_dirs(root)) == 4  # every periodic save committed
        final = rz.read_heartbeat(hb)
        assert final["ckpt_path"].endswith("step_0000000003")

    def test_shutdown_drain_never_regresses_emergency_pointer(
            self, tmp_path):
        """After escalate() publishes the emergency checkpoint, the
        shutdown drain must not overwrite the heartbeat's resume
        pointer with an OLDER async commit."""
        hb = str(tmp_path / "hb.json")
        sup = rz.TrainingSupervisor(
            rz.CheckpointManager(str(tmp_path / "c"), keep=20),
            _fast_config(checkpoint_every=1, async_save=True,
                         max_consecutive_failures=1,
                         heartbeat_path=hb))
        # an async commit for step 0, then escalation at step 6
        sup._async.save(0, {"w": jnp.arange(4.0)}).result()
        with pytest.raises(rz.TrainingAborted) as ei:
            sup.record_failure(6, {"w": jnp.ones(4)},
                               rz.StepDeadlineExceeded(6, 1.0, 2.0))
        assert ei.value.checkpoint_path.endswith("step_0000000006")
        # the drain run()'s finally performs: must be a no-op here
        sup._async.wait(timeout=5.0)
        sup._beat_if_newer(6)
        beat = rz.read_heartbeat(hb)
        assert beat["ckpt_path"] == ei.value.checkpoint_path
