"""Serving subsystem: KV-cached decode + continuous batching (ISSUE 4).

THE acceptance run: greedy incremental decode of >= 64 tokens through
the slotted KV cache on a GQA config (kv_heads < heads) is
**bit-identical** — same f32 logits and same argmax — to the uncached
full-context forward at each length.  The bit-exact reference is the
*shape-stable* uncached forward (context padded to the engine's
``max_len``, the recompile-free form a TPU server would actually run):
identical reduction extents make every step exactly equal.  Against the
*unpadded* uncached forward (whose XLA reductions re-associate per
length), the greedy argmax stream is asserted identical at every step
and logits agree to float tolerance — XLA's own lowering is the only
thing that moves.

Plus: slot eviction/reuse keeps other streams bit-identical, sampling
reproducible under fixed PRNG keys, FIFO continuous batching drains a
staggered mixed-length workload with no starvation, v1/v2 checkpoints
load into the engine, and the decode step compiles exactly once.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.serving.kv_cache import (
    append_token,
    init_cache,
    prefill_into_slot,
    release_slot,
    valid_token_mask,
)

# GQA on purpose: kv_heads (2) < heads (4) exercises the cache's grouped
# broadcast (the acceptance criterion names this config class)
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96        # cache capacity for the parity runs


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    ids = jnp.zeros((1, 4), jnp.int32)
    return model.init(jax.random.PRNGKey(0), ids)


@pytest.fixture(scope="module")
def full_fwd(model):
    return jax.jit(lambda p, ids: model.apply(p, ids))


def _padded_ref(full_fwd, params, tokens, pad_to=MAX):
    """Shape-stable uncached forward: context padded to ``pad_to``,
    next-token logits at the last real position (f32)."""
    ids = np.zeros((1, pad_to), np.int32)
    ids[0, :len(tokens)] = tokens
    return full_fwd(params, jnp.asarray(ids))[len(tokens) - 1, 0].astype(
        jnp.float32)


def _unpadded_ref(full_fwd, params, tokens):
    ids = jnp.asarray([list(tokens)], jnp.int32)
    return full_fwd(params, ids)[-1, 0].astype(jnp.float32)


def _prompt(seed=0, n=5):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, CFG.vocab_size, n)]


# ---------------------------------------------------------------------------
# THE acceptance run: cached decode == uncached forward, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.slow   # ~58 s: tier-1 keeps cheaper witnesses of the same
# cached==uncached claim (test_long_prompt_chunked_prefill_bit_identical
# plus both checkpoint-loads-and-serves tests, all asserting decode
# output against full_fwd)
def test_greedy_decode_bit_identical_to_uncached(model, params, full_fwd):
    # prefill_len == max_len: prefill shares the decode steps' reduction
    # extents, so the whole stream (first token included) is bit-exact
    eng = sv.DecodeEngine(model, params, slots=4, max_len=MAX,
                          prefill_len=MAX)
    toks = _prompt()
    logits = eng.prefill(0, toks)
    assert bool(jnp.all(logits == _padded_ref(full_fwd, params, toks)))

    n_steps = 70                      # prompt 5 + 70 > the 64-token bar
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits))
        toks.append(nxt)
        step_logits = eng.decode(
            np.array([nxt, 0, 0, 0], np.int32),
            np.array([True, False, False, False]))
        logits = step_logits[0]
        # bit-identical vs the shape-stable uncached forward
        ref = _padded_ref(full_fwd, params, toks)
        assert bool(jnp.all(logits == ref)), (
            f"decode diverged from uncached forward at length {len(toks)}")
        # same greedy choice as the unpadded forward, logits within float
        # tolerance (XLA re-associates its reductions per input length —
        # that lowering artifact is the entire difference)
        unp = _unpadded_ref(full_fwd, params, toks)
        assert int(jnp.argmax(logits)) == int(jnp.argmax(unp))
        np.testing.assert_allclose(np.asarray(logits), np.asarray(unp),
                                   rtol=1e-5, atol=1e-5)
    assert eng.decode_compiles() == 1


def test_prefill_is_shape_stable_forward_plus_cache_fill(model, params,
                                                         full_fwd):
    """Prefill logits equal the shape-stable uncached forward (context
    padded to ``max_len``) — the chunk's cached read shares the decode
    path's reduction extents, so the bucket a prompt lands in never
    moves a bit."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=8)
    toks = _prompt(n=6)
    got = eng.prefill(0, toks)
    want = _padded_ref(full_fwd, params, toks)
    assert bool(jnp.all(got == want))
    assert eng.lengths()[0] == 6 and eng.lengths()[1] == 0
    # one bucket table entry (prefill_len=8 -> (8,)), one compile
    assert eng.prefill_buckets == (8,)
    assert eng.prefill_compiles() == 1


def test_bucket_table_defaults_and_validation(model, params):
    assert sv.default_prefill_buckets(8) == (8,)
    assert sv.default_prefill_buckets(16) == (16,)
    assert sv.default_prefill_buckets(96) == (16, 32, 64, 96)
    assert sv.default_prefill_buckets(128) == (16, 32, 64, 128)
    eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=MAX)
    assert eng.prefill_buckets == (16, 32, 64, 96)
    assert eng.bucket_for(1) == 16 and eng.bucket_for(16) == 16
    assert eng.bucket_for(17) == 32 and eng.bucket_for(96) == 96
    with pytest.raises(ValueError):           # beyond the chunk ceiling
        eng.bucket_for(97)
    with pytest.raises(ValueError):           # not ascending
        sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                        prefill_len=8, prefill_buckets=(8, 4))
    with pytest.raises(ValueError):           # last != prefill_len
        sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                        prefill_len=8, prefill_buckets=(4,))
    with pytest.raises(ValueError):           # 1-row chunk ambiguous
        sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                        prefill_len=8, prefill_buckets=(1, 8))


# ---------------------------------------------------------------------------
# slot lifecycle: eviction + immediate reuse, streams stay bit-identical
# ---------------------------------------------------------------------------


def test_eviction_and_reuse_keep_other_streams_bit_identical(model, params):
    """Stream A decodes alone; then again while B finishes early (slot
    evicted) and C is admitted into B's freed slot mid-flight.  A's
    per-step logits must not move by a single bit."""
    def run_solo(n_steps):
        eng = sv.DecodeEngine(model, params, slots=3, max_len=MAX,
                              prefill_len=8)
        toks = _prompt(seed=1)
        logits = eng.prefill(0, toks)
        out = []
        for _ in range(n_steps):
            nxt = int(jnp.argmax(logits))
            logits = eng.decode(np.array([nxt, 0, 0], np.int32),
                                np.array([True, False, False]))[0]
            out.append(np.asarray(logits))
        return out

    solo = run_solo(12)

    eng = sv.DecodeEngine(model, params, slots=3, max_len=MAX,
                          prefill_len=8)
    a_logits = eng.prefill(0, _prompt(seed=1))
    b_logits = eng.prefill(1, _prompt(seed=2))
    got = []
    c_logits = None
    for step in range(12):
        tokens = np.zeros((3,), np.int32)
        active = np.zeros((3,), bool)
        tokens[0], active[0] = int(jnp.argmax(a_logits)), True
        if step < 4:                       # B alive for 4 steps
            tokens[1], active[1] = int(jnp.argmax(b_logits)), True
        elif step == 4:                    # evict B, admit C into slot 1
            eng.release(1)
            c_logits = eng.prefill(1, _prompt(seed=3, n=3))
        if c_logits is not None:
            tokens[1], active[1] = int(jnp.argmax(c_logits)), True
        step_logits = eng.decode(tokens, active)
        a_logits = step_logits[0]
        if active[1] and c_logits is not None:
            c_logits = step_logits[1]
        elif active[1]:
            b_logits = step_logits[1]
        got.append(np.asarray(a_logits))

    for t, (a, b) in enumerate(zip(solo, got)):
        assert np.array_equal(a, b), f"stream A diverged at step {t}"
    assert eng.decode_compiles() == 1


def test_kv_cache_primitive_updates():
    cache = init_cache(CFG, slots=3, max_len=16)
    assert cache.num_layers == 2 and cache.num_slots == 3
    assert cache.max_len == 16

    hd = CFG.hidden_size // CFG.num_attention_heads
    k_seq = jnp.ones((4, CFG.kv_heads, hd))
    cache2 = prefill_into_slot(cache, 1, slot=2, k_seq=k_seq, v_seq=2 * k_seq)
    k_np = np.asarray(cache2.k)
    assert k_np[1, 2, :4].sum() == 4 * CFG.kv_heads * hd   # written
    assert k_np[1, 2, 4:].sum() == 0                       # past the prompt
    assert k_np[0].sum() == 0 and k_np[1, :2].sum() == 0   # other layers/slots

    tok = jnp.full((3, CFG.kv_heads, hd), 7.0)
    cache3 = append_token(cache2, 0, tok, tok, positions=jnp.asarray([0, 5, 9]))
    k0 = np.asarray(cache3.k)[0]
    assert (k0[0, 0] == 7).all() and (k0[1, 5] == 7).all() \
        and (k0[2, 9] == 7).all()
    assert k0[0, 1:].sum() == 0 and k0[1, :5].sum() == 0

    cache4 = release_slot(
        cache3.__class__(cache3.k, cache3.v,
                         jnp.asarray([3, 2, 1], jnp.int32)), 1)
    assert np.asarray(cache4.lengths).tolist() == [3, 0, 1]

    mask = np.asarray(valid_token_mask(jnp.asarray([0, 2]), 5))
    assert mask.dtype == bool
    assert mask.astype(int).tolist() == [[1, 0, 0, 0, 0], [1, 1, 1, 0, 0]]


# ---------------------------------------------------------------------------
# sampling: deterministic under explicit keys
# ---------------------------------------------------------------------------


def test_sampling_reproducible_under_fixed_keys(model, params):
    def run(seed, temperature=0.9, top_k=8, n=16):
        eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                              prefill_len=8)
        sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9)
        sched.submit(sv.Request("r", _prompt(), max_new_tokens=n,
                                temperature=temperature, top_k=top_k,
                                seed=seed))
        return sched.run()["r"].tokens

    a, b = run(7), run(7)
    assert a == b, "same seed must reproduce the same stream"
    c = run(8)
    assert a != c, "different seeds should diverge (16 draws, k=8)"


def test_topk_one_is_greedy_and_topk_masks(model, params):
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=8)
    logits = eng.prefill(0, _prompt())[None]        # [1, vocab]
    key = sv.request_key(3)[None]
    # top_k=1 at any temperature can only pick the argmax
    tok = eng.sample(logits, key, np.int32([0]), np.float32([5.0]),
                     np.int32([1]))
    assert int(tok[0]) == int(jnp.argmax(logits[0]))
    # top_k=4 samples must come from the 4 highest logits
    top4 = set(np.argsort(np.asarray(logits[0]))[-4:].tolist())
    for i in range(20):
        t = eng.sample(logits, sv.request_key(i)[None], np.int32([i]),
                       np.float32([1.5]), np.int32([4]))
        assert int(t[0]) in top4
    # sampling is a pure function of (base_key, index)
    a = eng.sample(logits, sv.request_key(5)[None], np.int32([7]),
                   np.float32([1.0]), np.int32([0]))
    b = eng.sample(logits, sv.request_key(5)[None], np.int32([7]),
                   np.float32([1.0]), np.int32([0]))
    assert int(a[0]) == int(b[0])
    # temperature<=0 ignores the key entirely (pure argmax)
    t0 = eng.sample(logits, key, np.int32([0]), np.float32([0.0]),
                    np.int32([0]))
    assert int(t0[0]) == int(jnp.argmax(logits[0]))


# ---------------------------------------------------------------------------
# continuous batching: admission, drain, no starvation, compile-once
# ---------------------------------------------------------------------------


def test_scheduler_drains_staggered_mixed_workload(model, params):
    """More requests than slots, mixed prompt/output lengths, arrivals
    staggered across step boundaries: everything completes, admission is
    FIFO (no starvation), and the decode step never retraces."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=8)
    admitted = []
    orig_chunk = eng.prefill_chunk

    def spy_chunk(slot, tokens):
        # every prompt here fits one chunk, so first-chunk order IS
        # admission order
        admitted.append(tuple(tokens))
        return orig_chunk(slot, tokens)

    eng.prefill_chunk = spy_chunk
    sched = sv.ContinuousBatchingScheduler(eng, max_queue=8,
                                           log_interval=10 ** 9)
    reqs = [sv.Request(f"r{i}", _prompt(seed=i, n=2 + i % 5),
                       max_new_tokens=3 + (i % 4)) for i in range(6)]
    pending = list(reqs)
    sched.submit(pending.pop(0))
    results = {}
    for _ in range(400):
        if pending:
            sched.submit(pending.pop(0))   # staggered: one per boundary
        sched.step()
        results = sched.results
        if not pending and len(results) == len(reqs):
            break
    assert len(results) == len(reqs), (
        f"workload did not drain: {sorted(results)}")
    for r in reqs:
        got = results[r.rid]
        assert len(got.tokens) == r.max_new_tokens
        assert got.finish_reason == "length"
        assert got.ttft_s >= 0.0 and got.tokens_per_s > 0.0
    # FIFO admission = submission order (starvation-freedom witness)
    assert admitted == [tuple(r.prompt) for r in reqs]
    assert eng.decode_compiles() == 1


def test_scheduler_eos_eviction_and_immediate_reuse(model, params):
    """A request whose stream hits EOS frees its slot at that boundary;
    a queued request is admitted into the SAME slot and completes."""
    eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=8)
    # probe: find the first greedy token so we can use it as the EOS id
    probe_logits = eng.prefill(0, _prompt(seed=4))
    eos = int(jnp.argmax(probe_logits))
    eng.release(0)

    sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9)
    sched.submit(sv.Request("stops", _prompt(seed=4), max_new_tokens=50,
                            eos_id=eos))
    sched.submit(sv.Request("next", _prompt(seed=5), max_new_tokens=4))
    results = sched.run()
    assert results["stops"].finish_reason == "eos"
    assert results["stops"].tokens == [eos]
    assert results["next"].finish_reason == "length"
    assert len(results["next"].tokens) == 4
    assert eng.free_slots() == [0]


def test_queue_and_validation_limits(model, params):
    eng = sv.DecodeEngine(model, params, slots=1, max_len=32,
                          prefill_len=8)
    sched = sv.ContinuousBatchingScheduler(eng, max_queue=2)
    sched.submit(sv.Request("a", [1], max_new_tokens=1))
    sched.submit(sv.Request("b", [1], max_new_tokens=1))
    with pytest.raises(sv.QueueFull):
        sched.submit(sv.Request("c", [1], max_new_tokens=1))
    with pytest.raises(ValueError):           # prompt beyond cache capacity
        sched.submit(sv.Request("d", [1] * 33, max_new_tokens=1))
    with pytest.raises(ValueError):           # would overrun the cache
        sched.submit(sv.Request("e", [1] * 4, max_new_tokens=40))
    with pytest.raises(ValueError):           # engine-level capacity check
        eng.prefill(0, [1] * 33)
    with pytest.raises(ValueError):
        sv.DecodeEngine(model, params, slots=1, max_len=8, prefill_len=16)
    with pytest.raises(ValueError):           # zero-token requests
        sched.submit(sv.Request("f", [1], max_new_tokens=0))
    with pytest.raises(ValueError):           # zero-token prefill budget
        sv.ContinuousBatchingScheduler(eng, prefill_budget=0)
    with pytest.raises(ValueError):           # duplicate rid (queued)
        sched.submit(sv.Request("a", [2], max_new_tokens=1))
    with pytest.raises(ValueError):           # slot out of range
        eng.prefill(5, [1, 2])
    eng2 = sv.DecodeEngine(model, params, slots=1, max_len=8,
                           prefill_len=8)
    with pytest.raises(ValueError):           # decode on a free slot
        eng2.decode(np.array([1], np.int32), np.array([True]))
    eng2.prefill(0, [1] * 8)                  # slot now full
    with pytest.raises(ValueError):           # prefill over a live stream
        eng2.prefill(0, [1, 2])
    with pytest.raises(ValueError):           # decode past cache capacity
        eng2.decode(np.array([1], np.int32), np.array([True]))
    # exact-fit admission: the final sampled token is never cached, so
    # prompt 4 + 5 new tokens peaks at position 7 in an 8-slot cache
    eng3 = sv.DecodeEngine(model, params, slots=1, max_len=8,
                           prefill_len=8)
    sched3 = sv.ContinuousBatchingScheduler(eng3, log_interval=10 ** 9)
    sched3.submit(sv.Request("fit", [1] * 4, max_new_tokens=5))
    assert len(sched3.run()["fit"].tokens) == 5
    with pytest.raises(ValueError):           # serving mode rejects labels
        ids = jnp.zeros((1, 4), jnp.int32)
        model.apply(params, ids, labels=ids,
                    kv_cache=eng.cache, slot=jnp.int32(0))
    with pytest.raises(ValueError):           # chunk past cache capacity
        eng3b = sv.DecodeEngine(model, params, slots=1, max_len=8,
                                prefill_len=8)
        eng3b.prefill_chunk(0, [1] * 6)
        eng3b.prefill_chunk(0, [1] * 6)       # offset 6 + 6 > 8


# ---------------------------------------------------------------------------
# chunked cached prefill: prompts past prefill_len, bucketed compiles,
# prefill/decode interleaving (ISSUE 7)
# ---------------------------------------------------------------------------


def test_long_prompt_chunked_prefill_bit_identical(model, params, full_fwd):
    """THE ISSUE-7 acceptance run: a prompt LONGER than ``prefill_len``
    (70 > 16) is served via chunked cached prefill — every chunk's
    causal block reads the previously cached tokens through the masked
    fixed-extent path — and both the first-token logits and the whole
    greedy decode stream are bit-identical to the shape-stable uncached
    forward.  Compile count stays bounded by the bucket table."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16)
    toks = _prompt(n=70)                  # chunks 16/16/16/16 + tail 6
    logits = eng.prefill(0, toks)
    assert bool(jnp.all(logits == _padded_ref(full_fwd, params, toks)))
    for _ in range(20):
        nxt = int(jnp.argmax(logits))
        toks.append(nxt)
        logits = eng.decode(np.array([nxt, 0], np.int32),
                            np.array([True, False]))[0]
        ref = _padded_ref(full_fwd, params, toks)
        assert bool(jnp.all(logits == ref)), (
            f"decode diverged from uncached forward at length {len(toks)}"
            f" after a chunked prefill")
    # prefill_len=16 -> bucket table (16,): full chunks AND the 6-token
    # tail share the single bucket program
    assert eng.prefill_buckets == (16,)
    assert eng.prefill_compiles() == 1
    assert eng.decode_compiles() == 1


def test_bucket_padding_overhang_never_clobbers_cached_tokens(
        model, params, full_fwd):
    """A bucket-padded tail chunk near the cache end (start + bucket >
    max_len even though every REAL token fits) must DROP its overhanging
    padding rows: a clamped block write would silently shift backward
    onto previously cached real K/V.  max_len=90 is deliberately not
    bucket-aligned — the 26-token tail of a 90-token prompt pads to a
    32-row bucket at offset 64, overhanging by 6."""
    small = 90
    eng = sv.DecodeEngine(model, params, slots=1, max_len=small,
                          prefill_len=64)
    toks = _prompt(n=small)               # chunks: 64 + tail 26 (bucket 32)
    logits = eng.prefill(0, toks)
    ref = _padded_ref(full_fwd, params, toks, pad_to=small)
    assert bool(jnp.all(logits == ref)), (
        "prefill near the cache end diverged — the padded tail write "
        "clobbered cached K/V")


@pytest.mark.slow
def test_bucket_padding_overhang_scheduler_route(model, params, full_fwd):
    """Scheduler route of the overhang claim: budget fragmentation
    lands a tiny tail at an unaligned offset (88 + bucket 8 > 90); the
    stream must still produce the uncached forward's greedy tokens.
    Slow-tier (its own 3-bucket table at an off-size max_len is a fresh
    compile set); the direct-engine overhang witness above stays
    tier-1."""
    small = 90
    toks = _prompt(n=small)
    eng2 = sv.DecodeEngine(model, params, slots=1, max_len=small,
                           prefill_len=64, prefill_buckets=(8, 16, 64))
    sched = sv.ContinuousBatchingScheduler(eng2, log_interval=10 ** 9,
                                           prefill_budget=11)
    sched.submit(sv.Request("edge", toks[:89], max_new_tokens=2))
    out = sched.run()["edge"].tokens
    want = list(toks[:89])
    for t in out[:1]:
        assert t == int(jnp.argmax(_padded_ref(full_fwd, params, want,
                                               pad_to=small)))
        want.append(t)


def test_chunk_split_never_changes_bits(model, params):
    """The same prompt through one-shot prefill vs manual uneven chunks
    yields the SAME logits bit-for-bit — chunk boundaries are an
    implementation detail, not a numerics knob.  (Tier-1 witness at the
    single-bucket size; the multi-bucket sweep is the slow-marked
    variant below.)"""
    toks = _prompt(n=16)
    eng1 = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=16)
    one = eng1.prefill(0, toks)
    eng2 = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=16)
    for lo, hi in ((0, 3), (3, 10), (10, 16)):
        chunked = eng2.prefill_chunk(0, toks[lo:hi])
    assert bool(jnp.all(one == chunked))
    assert eng2.lengths()[0] == 16


@pytest.mark.slow
def test_chunk_split_never_changes_bits_multi_bucket(model, params):
    """The uneven-manual-chunks equality sweep at the multi-bucket
    config (prefill_len=64 — chunk lengths land in three different
    buckets): compile-heavy, so slow-tier; the single-bucket tier-1
    variant above keeps the claim family witnessed."""
    toks = _prompt(n=40)
    eng1 = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=64)
    one = eng1.prefill(0, toks)
    eng2 = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                           prefill_len=64)
    for lo, hi in ((0, 3), (3, 20), (20, 33), (33, 40)):
        chunked = eng2.prefill_chunk(0, toks[lo:hi])
    assert bool(jnp.all(one == chunked))
    assert eng2.lengths()[0] == 40


@pytest.mark.slow
def test_mixed_prompt_length_drain_bounded_compiles_fifo(model, params):
    """ISSUE-7 satellite: a mixed drain over lengths 1, 63, 64, 65,
    prefill_len and > prefill_len — bounded prefill compiles (the
    bucket table), FIFO no-starvation, every stream completes.
    Slow-tier (a 5-entry bucket table is the compile-heaviest serving
    config in the suite); FIFO drain and the compile bounds keep tier-1
    witnesses in ``test_scheduler_drains_staggered_mixed_workload`` and
    ``test_long_prompt_chunked_prefill_bit_identical``."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=80,
                          prefill_buckets=(8, 16, 32, 64, 80))
    first_chunks = []
    orig_chunk = eng.prefill_chunk

    def spy_chunk(slot, tokens):
        if eng.lengths()[slot] == 0:      # first chunk == admission
            first_chunks.append(tuple(tokens[:4]))
        return orig_chunk(slot, tokens)

    eng.prefill_chunk = spy_chunk
    sched = sv.ContinuousBatchingScheduler(eng, max_queue=8,
                                           log_interval=10 ** 9,
                                           prefill_budget=32)
    lens = [1, 63, 64, 65, 80, 90]        # 80 == prefill_len, 90 > it
    reqs = [sv.Request(f"r{i}", _prompt(seed=i, n=n), max_new_tokens=3)
            for i, n in enumerate(lens)]
    for r in reqs:
        sched.submit(r)
    results = sched.run()
    assert sorted(results) == sorted(r.rid for r in reqs)
    for r in reqs:
        assert len(results[r.rid].tokens) == 3
        assert results[r.rid].finish_reason == "length"
    # FIFO: first chunks dispatch in submission order (no starvation)
    assert first_chunks == [tuple(r.prompt[:4]) for r in reqs]
    # compile count bounded by the bucket table, asserted not hoped
    assert eng.prefill_compiles() <= len(eng.prefill_buckets)
    assert eng.decode_compiles() == 1
    assert sched.prefill_backlog == 0


def test_neighbor_slot_bit_identical_during_interleaved_chunked_prefill(
        model, params):
    """While a long prompt prefills chunk-by-chunk in slot 1, stream A
    keeps decoding in slot 0 — and its per-step logits must not move by
    a single bit vs decoding alone (chunk writes touch only their own
    slot; interleaving is scheduling, not numerics)."""
    def run_a(interleave):
        eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                              prefill_len=16)
        a_logits = eng.prefill(0, _prompt(seed=1))
        long_prompt = _prompt(seed=9, n=64)
        out = []
        for step in range(12):
            if interleave and step < 4:   # one 16-token chunk per step
                eng.prefill_chunk(
                    1, long_prompt[step * 16:(step + 1) * 16])
            nxt = int(jnp.argmax(a_logits))
            a_logits = eng.decode(np.array([nxt, 0], np.int32),
                                  np.array([True, False]))[0]
            out.append(np.asarray(a_logits))
        return out

    solo = run_a(interleave=False)
    interleaved = run_a(interleave=True)
    for t, (a, b) in enumerate(zip(solo, interleaved)):
        assert np.array_equal(a, b), (
            f"stream A diverged at step {t} during neighbor prefill")


def test_prefill_budget_defers_work_and_reports_backlog(model, params):
    """A 40-token prompt under an 8-token/step budget takes 5 steps to
    cache: the deferred remainder is visible as prefill_backlog (and
    the obs gauge), the first token arrives only when the prompt
    completes, and decode of a live stream proceeds every step."""
    from apex_tpu.obs import bridge as obs_bridge

    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, prefill_buckets=(8, 16))
    sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9,
                                           prefill_budget=8)
    sched.submit(sv.Request("short", _prompt(seed=0, n=4),
                            max_new_tokens=16))
    sched.step()                          # short fully cached + tok 1
    assert sched.phase_of("short") is sv.RequestPhase.DECODE
    sched.submit(sv.Request("long", _prompt(seed=1, n=40),
                            max_new_tokens=2))
    backlogs = []
    first_at = None
    for i in range(8):
        sched.step()
        backlogs.append(sched.prefill_backlog)
        if first_at is None and sched.phase_of("long") in (
                sv.RequestPhase.DECODE, sv.RequestPhase.DONE):
            first_at = i
    # 40 tokens / 8-token budget -> 5 steps of chunks; backlog counts
    # down 32, 24, 16, 8, 0 while "short" keeps decoding throughout
    assert backlogs[:5] == [32, 24, 16, 8, 0]
    assert first_at == 4
    assert obs_bridge.SERVING_PREFILL_BACKLOG.value() == 0.0
    results = sched.run()
    assert len(results["long"].tokens) == 2
    assert len(results["short"].tokens) == 16


# ---------------------------------------------------------------------------
# the >=2x continuous-batching win (acceptance criterion 4)
# ---------------------------------------------------------------------------


@pytest.mark.slow   # ~6 s: a wall-clock throughput bar (host-dispatch
# dominated on CPU); the bench serving block measures the same claim
def test_concurrent_4_streams_at_least_2x_sequential(model, params):
    """4 concurrent streams through continuous batching must deliver
    >= 2x the aggregate tokens/s of 4 sequential single-stream runs.
    Wall-clock on a shared CI host flakes, so best-of-3 attempts."""
    def mk():
        eng = sv.DecodeEngine(model, params, slots=4, max_len=MAX,
                              prefill_len=8)
        return eng, sv.ContinuousBatchingScheduler(eng,
                                                   log_interval=10 ** 9)

    def requests():
        return [sv.Request(f"r{i}", _prompt(seed=i), max_new_tokens=32)
                for i in range(4)]

    best = 0.0
    for _ in range(3):
        # sequential: one stream at a time, same engine (warm compiles)
        eng, sched = mk()
        sched.submit(sv.Request("warm", _prompt(), max_new_tokens=2))
        sched.run()
        t0 = time.perf_counter()
        n_seq = 0
        for r in requests():
            sched.submit(r)
            n_seq += len(sched.run()[r.rid].tokens)
        t_seq = time.perf_counter() - t0

        # concurrent: all four in flight
        eng2, sched2 = mk()
        sched2.submit(sv.Request("warm", _prompt(), max_new_tokens=2))
        sched2.run()
        t0 = time.perf_counter()
        for r in requests():
            sched2.submit(r)
        n_con = sum(len(x.tokens) for x in sched2.run().values()
                    if x.rid != "warm")
        t_con = time.perf_counter() - t0

        speedup = (n_con / t_con) / (n_seq / t_seq)
        best = max(best, speedup)
        if best >= 2.0:
            break
    assert best >= 2.0, f"continuous batching speedup {best:.2f} < 2x"


# ---------------------------------------------------------------------------
# weights: serve from resilience checkpoints (v1 + v2 sharded)
# ---------------------------------------------------------------------------


def test_v1_checkpoint_loads_and_serves(model, params, full_fwd, tmp_path):
    from apex_tpu import amp
    from apex_tpu.resilience import save_checkpoint

    state = {"params": params, "step": jnp.int32(7)}
    save_checkpoint(str(tmp_path), 7, state)
    got, step = sv.load_serving_params(str(tmp_path), like=state,
                                       params_key="params")
    assert step == 7
    eng = sv.DecodeEngine(model, got, slots=1, max_len=MAX, prefill_len=8)
    toks = _prompt()
    logits = eng.prefill(0, toks)
    nxt = int(jnp.argmax(logits))
    dec = eng.decode(np.array([nxt], np.int32), np.array([True]))[0]
    toks.append(nxt)
    assert bool(jnp.all(dec == _padded_ref(full_fwd, params, toks)))

    # bf16 serving cast through amp.policy: matmul weights cast, norm
    # scales pinned fp32 (the keep_norm_fp32 contract)
    cast, _ = sv.load_serving_params(str(tmp_path), like=state,
                                     params_key="params",
                                     policy=amp.policy.O2())
    p = cast["params"]
    assert p["lm_head"].dtype == jnp.bfloat16
    assert p["layers_0"]["self_attn"]["q_proj"]["kernel"].dtype == jnp.bfloat16
    assert p["norm"]["scale"].dtype == jnp.float32
    # a bf16 engine infers a bf16 cache and still decodes
    eng16 = sv.DecodeEngine(model, cast, slots=1, max_len=32, prefill_len=8)
    assert eng16.cache.dtype == jnp.bfloat16
    l16 = eng16.prefill(0, _prompt())
    assert np.isfinite(np.asarray(l16)).all()


def test_v2_sharded_checkpoint_loads_and_serves(model, params, full_fwd,
                                                devices, tmp_path):
    from jax.sharding import Mesh

    from apex_tpu.resilience import save_sharded_checkpoint

    mesh = Mesh(np.array(devices[:4]).reshape(4), ("dp",))
    state = {"params": params, "step": jnp.int32(3)}
    save_sharded_checkpoint(str(tmp_path), 3, state, mesh=mesh)
    got, step = sv.load_serving_params(str(tmp_path), like=state,
                                       params_key="params")
    assert step == 3
    eng = sv.DecodeEngine(model, got, slots=2, max_len=MAX, prefill_len=8)
    toks = _prompt()
    logits = eng.prefill(0, toks)
    nxt = int(jnp.argmax(logits))
    toks.append(nxt)
    dec = eng.decode(np.array([nxt, 0], np.int32),
                     np.array([True, False]))[0]
    assert bool(jnp.all(dec == _padded_ref(full_fwd, params, toks)))


def test_load_serving_params_failure_modes(params, tmp_path):
    from apex_tpu.resilience import CheckpointError, save_checkpoint

    with pytest.raises(CheckpointError):      # empty root
        sv.load_serving_params(str(tmp_path), like={"params": params})
    state = {"params": params}
    save_checkpoint(str(tmp_path), 0, state)
    with pytest.raises(CheckpointError):      # missing subtree key
        sv.load_serving_params(str(tmp_path), like=state,
                               params_key="nope")

    # a corrupt NEWEST step falls back to the older valid one — the
    # training-restart contract, on the serving path
    save_checkpoint(str(tmp_path), 1, state, keep=3)
    data = tmp_path / "step_0000000001" / "data.bin"
    data.write_bytes(data.read_bytes()[:-8] + b"\x00" * 8)
    got, step = sv.load_serving_params(str(tmp_path), like=state,
                                       params_key="params")
    assert step == 0
    # pinned step does NOT fall back
    with pytest.raises(CheckpointError):
        sv.load_serving_params(str(tmp_path), like=state, step=1)


def test_scheduler_pop_results_frees_rids(model, params):
    eng = sv.DecodeEngine(model, params, slots=1, max_len=32,
                          prefill_len=8)
    sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9)
    sched.submit(sv.Request("r", [1, 2], max_new_tokens=2))
    sched.run()
    with pytest.raises(ValueError):           # rid still claimed
        sched.submit(sv.Request("r", [1, 2], max_new_tokens=2))
    first = sched.pop_result("r")
    assert len(first.tokens) == 2 and sched.results == {}
    sched.submit(sv.Request("r", [1, 2], max_new_tokens=2))  # reusable now
    again = sched.run()["r"]
    assert again.tokens == first.tokens       # same seed -> same stream


# ---------------------------------------------------------------------------
# long decode (slow: excluded from tier-1 by the 'not slow' filter)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_long_decode_512_tokens_stays_on_stream(model, params, full_fwd):
    """512 generated tokens through one slot: the greedy stream tracks
    the uncached forward at every probe and the step never retraces.

    Bit-exactness is pinned (in tier-1) at serving-sized caches; at this
    cache size the *reference* side's [520, 520] gemms cross into a
    different XLA kernel choice than small-M decode blocks, so the
    long-horizon contract is argmax-identity + float tolerance."""
    big = 520
    eng = sv.DecodeEngine(model, params, slots=2, max_len=big,
                          prefill_len=big)
    toks = _prompt()
    logits = eng.prefill(0, toks)
    for t in range(512):
        nxt = int(jnp.argmax(logits))
        toks.append(nxt)
        logits = eng.decode(np.array([nxt, 0], np.int32),
                            np.array([True, False]))[0]
        if t % 64 == 0:
            ref = _padded_ref(full_fwd, params, toks, pad_to=big)
            assert int(jnp.argmax(logits)) == int(jnp.argmax(ref)), (
                f"greedy stream left the uncached stream at {len(toks)}")
            np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
    assert np.isfinite(np.asarray(logits)).all()
    assert eng.decode_compiles() == 1
