"""Megatron-style argument parsing + global singletons."""

import jax.numpy as jnp
import pytest

from apex_tpu.transformer.testing import (
    core_transformer_config_from_args,
    destroy_global_vars,
    get_args,
    get_current_global_batch_size,
    get_num_microbatches,
    get_timers,
    parse_args,
    set_global_variables,
    update_num_microbatches,
)


@pytest.fixture(autouse=True)
def _clean_globals():
    destroy_global_vars()
    yield
    destroy_global_vars()


GPT_ARGS = [
    "--num-layers", "4", "--hidden-size", "64", "--num-attention-heads", "4",
    "--seq-length", "128", "--max-position-embeddings", "128",
    "--micro-batch-size", "2", "--global-batch-size", "16",
    "--vocab-size", "1024", "--lr", "1e-4", "--bf16",
]


def test_parse_args_derivations(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "8")
    args = parse_args(args_list=GPT_ARGS + [
        "--tensor-model-parallel-size", "2",
        "--pipeline-model-parallel-size", "2"])
    assert args.data_parallel_size == 2
    assert args.ffn_hidden_size == 4 * 64
    assert args.kv_channels == 16
    assert args.params_dtype == jnp.bfloat16
    cfg = core_transformer_config_from_args(args)
    assert cfg["vocab_size"] == 1024 and cfg["max_sequence_length"] == 128


def test_parse_args_validation(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "4")
    with pytest.raises(ValueError):
        parse_args(args_list=GPT_ARGS + [
            "--tensor-model-parallel-size", "3"])
    with pytest.raises(ValueError):
        parse_args(args_list=GPT_ARGS + ["--fp16"])  # fp16+bf16


def test_virtual_pipeline_derivation(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "8")
    args = parse_args(args_list=GPT_ARGS + [
        "--pipeline-model-parallel-size", "2",
        "--num-layers-per-virtual-pipeline-stage", "1"])
    assert args.virtual_pipeline_model_parallel_size == 2  # 4 layers/2pp/1
    with pytest.raises(ValueError):
        parse_args(args_list=GPT_ARGS + [
            "--pipeline-model-parallel-size", "2",
            "--num-layers-per-virtual-pipeline-stage", "3"])


def test_missing_required_args_clear_error(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "1")
    with pytest.raises(ValueError, match="--num-layers is required"):
        parse_args(args_list=["--micro-batch-size", "2"])


def test_failed_init_leaves_globals_clean(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "1")
    with pytest.raises(ValueError):
        set_global_variables(args_list=GPT_ARGS + [
            "--rampup-batch-size", "4", "2"])  # needs 3 values
    # retry after fixing succeeds — no poisoned half-initialized singleton
    set_global_variables(args_list=GPT_ARGS)
    assert get_args().hidden_size == 64


def test_fp16_defaults_dynamic_scale(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "1")
    args = parse_args(args_list=[a for a in GPT_ARGS if a != "--bf16"]
                      + ["--fp16"])
    assert args.params_dtype == jnp.float16
    assert args.loss_scale == "dynamic"


def test_global_vars_lifecycle(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "1")
    with pytest.raises(RuntimeError):
        get_args()
    set_global_variables(args_list=GPT_ARGS)
    assert get_args().hidden_size == 64
    assert get_num_microbatches() == 8  # 16 / (2 * dp=1)
    assert get_current_global_batch_size() == 16
    update_num_microbatches(100)
    t = get_timers()
    with t("demo").timing():
        pass
    assert t("demo").elapsed() >= 0
    with pytest.raises(RuntimeError):
        set_global_variables(args_list=GPT_ARGS)  # double init


def test_rampup_flows_through_globals(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "1")
    set_global_variables(args_list=GPT_ARGS + [
        "--rampup-batch-size", "4", "2", "100"])
    assert get_current_global_batch_size() == 4
    update_num_microbatches(200, consistency_check=True)
    assert get_current_global_batch_size() == 16
