"""Expert-parallel MoE: sharded all_to_all path vs the local oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.moe import ExpertParallelMLP, top1_dispatch


def test_top1_dispatch_capacity_and_loss():
    logits = jnp.asarray([[5.0, 0.0], [4.0, 0.0], [3.0, 0.0], [0.0, 2.0]],
                         jnp.float32)
    dispatch, combine, aux = top1_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    # tokens 0,1 fill expert 0's two slots; token 2 dropped (over capacity)
    assert d[0, 0, 0] == 1 and d[1, 0, 1] == 1
    assert d[2].sum() == 0
    assert d[3, 1, 0] == 1
    # combine carries the gate probability
    probs = np.asarray(jax.nn.softmax(logits, -1))
    np.testing.assert_allclose(np.asarray(combine)[0, 0, 0], probs[0, 0],
                               rtol=1e-6)
    assert float(aux) > 0


def test_moe_local_forward_and_grads():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    m = ExpertParallelMLP(num_experts=4, hidden_size=16, ffn_hidden_size=32,
                          capacity_factor=2.0)
    params = m.init(jax.random.PRNGKey(0), x)
    out, aux = m.apply(params, x)
    assert out.shape == x.shape
    grads = jax.grad(lambda p: m.apply(p, x)[0].sum() + m.apply(p, x)[1])(
        params)
    assert all(np.all(np.isfinite(l)) for l in jax.tree.leaves(grads))
    assert np.abs(np.asarray(
        grads["params"]["router"])).max() > 0  # router learns


@pytest.mark.slow  # whole-stack MoE compile (~3 s); dispatch + the
# expert-parallel oracle match stay in tier-1
def test_moe_layer_in_transformer_stack():
    """ParallelTransformer(moe_num_experts=...) trains: the MoE MLP
    replaces the dense one in every layer and the load-balancing loss is
    sown; expert/router params receive real gradients."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        ParallelTransformer,
    )

    rng = np.random.default_rng(5)
    s, b, h = 8, 2, 16
    x = jnp.asarray(rng.standard_normal((s, b, h)), jnp.float32)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    stack = ParallelTransformer(num_layers=2, hidden_size=h,
                                num_attention_heads=4, moe_num_experts=4)

    def fn(x):
        variables = stack.init(jax.random.PRNGKey(0), x)
        # apply with params ONLY: passing the whole init variables would
        # hand sow the init-time moe_losses to append to (double count)
        out, aux_col = stack.apply({"params": variables["params"]}, x,
                                   mutable=["moe_losses"])
        aux = sum(jax.tree.leaves(aux_col["moe_losses"]))

        def loss(params):
            y, _ = stack.apply({"params": params}, x,
                               mutable=["moe_losses"])
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(variables["params"])
        assert len(jax.tree.leaves(aux_col["moe_losses"])) == 2  # one/layer
        g_expert = g["layer_0"]["mlp"]["experts"]
        return out, aux, g_expert["w_in"], g_expert["router"]

    with mesh1:
        out, aux, g_win, g_router = jax.jit(shard_map(
            fn, mesh=mesh1, in_specs=P(), out_specs=P(),
            **NO_REP_CHECK))(x)
    assert out.shape == x.shape
    assert float(aux) > 0
    for g in (g_win, g_router):
        g = np.asarray(g)
        assert np.all(np.isfinite(g)) and np.abs(g).max() > 0


def test_expert_parallel_matches_local():
    """The ep-sharded all_to_all path must equal the single-rank oracle.

    capacity_factor=4 keeps capacity from binding: with drops the two
    paths cut different queues (per-rank vs global — see moe.py docstring)
    and parity intentionally does not hold."""
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("ep",))
    rng = np.random.default_rng(1)
    tokens_per_rank, h = 16, 8
    x = jnp.asarray(rng.standard_normal((4 * tokens_per_rank, h)),
                    jnp.float32)

    local = ExpertParallelMLP(num_experts=4, hidden_size=h,
                              ffn_hidden_size=16, capacity_factor=4.0,
                              axis_name=None)
    sharded = ExpertParallelMLP(num_experts=4, hidden_size=h,
                                ffn_hidden_size=16, capacity_factor=4.0,
                                axis_name="ep")
    params = local.init(jax.random.PRNGKey(0), x)

    # oracle: all experts local, all tokens at once
    want, _ = local.apply(params, x)

    def fn(x_shard, full_params):
        # each rank keeps its token shard and its expert slice
        # static axis size (jax 0.4.x has no jax.lax.axis_size); psum of
        # a literal 1 folds to the axis size at trace time
        ep = int(jax.lax.psum(1, "ep"))
        r = jax.lax.axis_index("ep")
        local_e = 4 // ep
        slice_p = {
            "params": {
                "router": full_params["params"]["router"],
                "w_in": jax.lax.dynamic_slice_in_dim(
                    full_params["params"]["w_in"], r * local_e, local_e, 0),
                "w_out": jax.lax.dynamic_slice_in_dim(
                    full_params["params"]["w_out"], r * local_e, local_e, 0),
            }
        }
        out, aux = sharded.apply(slice_p, x_shard)
        return out

    with mesh:
        got = jax.jit(shard_map(fn, mesh=mesh, in_specs=(P("ep"), P()),
                                out_specs=P("ep"), **NO_REP_CHECK))(
            x, params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
