"""Ring attention (context parallelism) vs dense attention parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.flash_attention import mha_reference
from apex_tpu.transformer.context_parallel import ring_attention


def dense_reference(q, k, v, causal):
    return np.asarray(mha_reference(jnp.asarray(q, jnp.float32),
                                    jnp.asarray(k, jnp.float32),
                                    jnp.asarray(v, jnp.float32),
                                    causal=causal))


@pytest.fixture
def cp_mesh():
    return Mesh(np.array(jax.devices()[:8]), ("cp",))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(cp_mesh, causal):
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 4, 64, 16  # s_local = 8 per rank
    q = rng.standard_normal((b, h, s, d)).astype(np.float32)
    k = rng.standard_normal((b, h, s, d)).astype(np.float32)
    v = rng.standard_normal((b, h, s, d)).astype(np.float32)

    def fn(q, k, v):
        return ring_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), axis_name="cp", causal=causal)

    with cp_mesh:
        got = jax.jit(shard_map(
            fn, mesh=cp_mesh,
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"), **NO_REP_CHECK))(q, k, v)
    want = dense_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_dense(cp_mesh):
    rng = np.random.default_rng(1)
    b, h, s, d = 1, 2, 32, 8
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def ring_loss(q, k, v):
        out = ring_attention(q, k, v, axis_name="cp", causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def fn(q, k, v):
        # per-rank partial losses sum over the mesh: grads are exact shards
        return jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)

    with cp_mesh:
        g_ring = jax.jit(shard_map(
            fn, mesh=cp_mesh, in_specs=(P(None, None, "cp"),) * 3,
            out_specs=(P(None, None, "cp"),) * 3, **NO_REP_CHECK))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def test_parallel_attention_with_cp_matches_local():
    """ParallelAttention(context_parallel_axis='cp') on sequence shards
    reproduces the unsharded block — long-context wired into the model
    stack, rope positions offset per shard."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        ParallelAttention,
    )

    rng = np.random.default_rng(3)
    s, b, h, heads = 32, 2, 16, 4
    x = jnp.asarray(rng.standard_normal((s, b, h)), jnp.float32)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("tp", "cp"))
    dense_mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("tp",))

    attn_local = ParallelAttention(hidden_size=h, num_attention_heads=heads,
                                   apply_rope=True)
    attn_cp = ParallelAttention(hidden_size=h, num_attention_heads=heads,
                                apply_rope=True, context_parallel_axis="cp")

    with dense_mesh:
        params = jax.jit(shard_map(
            lambda x: attn_local.init(jax.random.PRNGKey(0), x),
            mesh=dense_mesh, in_specs=P(), out_specs=P(),
            **NO_REP_CHECK))(x)
        want = jax.jit(shard_map(
            lambda p, x: attn_local.apply(p, x), mesh=dense_mesh,
            in_specs=(P(), P()), out_specs=P(), **NO_REP_CHECK))(params, x)

    params = jax.tree.map(np.asarray, params)  # re-place on the cp mesh
    with mesh:
        got = jax.jit(shard_map(
            lambda p, x: attn_cp.apply(p, x), mesh=mesh,
            in_specs=(P(), P("cp")), out_specs=P("cp"),
            **NO_REP_CHECK))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # whole-stack cp compile (~3.5 s); ring-attention
# parity itself stays in tier-1 via the dense-match + grads tests
def test_full_transformer_stack_with_cp_matches_local():
    """ParallelTransformer (2 layers + rope) over cp shards == unsharded."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        ParallelTransformer,
    )

    rng = np.random.default_rng(4)
    s, b, h = 16, 2, 16
    x = jnp.asarray(rng.standard_normal((s, b, h)), jnp.float32)
    dense_mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("tp",))
    cp_mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("tp", "cp"))

    base = dict(num_layers=2, hidden_size=h, num_attention_heads=4,
                apply_rope=True, final_layernorm=True)
    local = ParallelTransformer(**base)
    cp = ParallelTransformer(**base, context_parallel_axis="cp")

    with dense_mesh:
        params = jax.jit(shard_map(
            lambda x: local.init(jax.random.PRNGKey(0), x),
            mesh=dense_mesh, in_specs=P(), out_specs=P(),
            **NO_REP_CHECK))(x)
        want = jax.jit(shard_map(
            lambda p, x: local.apply(p, x), mesh=dense_mesh,
            in_specs=(P(), P()), out_specs=P(), **NO_REP_CHECK))(params, x)
    params = jax.tree.map(np.asarray, params)
    with cp_mesh4:
        got = jax.jit(shard_map(
            lambda p, x: cp.apply(p, x), mesh=cp_mesh4,
            in_specs=(P(), P("cp")), out_specs=P("cp"),
            **NO_REP_CHECK))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-5)


def test_ring_attention_bf16_and_long_sequence(cp_mesh):
    rng = np.random.default_rng(2)
    b, h, s, d = 1, 2, 1024, 32  # 128 tokens per rank
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)

    with cp_mesh:
        got = jax.jit(shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="cp"),
            mesh=cp_mesh, in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"), **NO_REP_CHECK))(q, k, v)
    assert got.dtype == jnp.bfloat16
    want = dense_reference(np.asarray(q, np.float32),
                           np.asarray(k, np.float32),
                           np.asarray(v, np.float32), causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               rtol=0.1, atol=0.05)
