"""amp cast-list contract tests — port of the reference's L0/run_amp
behavioral suite (test_basic_casts.py, test_promotion.py) to the
policy-scoped functional namespace."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp import functional as F
from apex_tpu.amp.policy import get_policy

HALF = jnp.bfloat16
DTYPES = [jnp.float16, jnp.bfloat16, jnp.float32]


@pytest.fixture(autouse=True)
def _no_ambient_policy():
    # amp.initialize installs a process-wide policy; isolate from any test
    # that ran it earlier
    F.set_active_policy(None)
    yield
    F.set_active_policy(None)


def o1():
    return F.active_policy(get_policy("O1", half_dtype=HALF))


# --- basic casts (test_basic_casts.py run_layer_test semantics) ------------

def test_matmul_casts_to_half_under_o1():
    x = jnp.ones((4, 4), jnp.float32)
    with o1():
        assert F.matmul(x, x).dtype == HALF
        assert F.einsum("ij,jk->ik", x, x).dtype == HALF


def test_float_funcs_cast_to_fp32_under_o1():
    x = jnp.ones((8,), HALF)
    with o1():
        assert F.exp(x).dtype == jnp.float32
        assert F.sum(x).dtype == jnp.float32
        assert F.softmax(x).dtype == jnp.float32
        assert F.linalg_norm(x).dtype == jnp.float32


def test_no_policy_is_passthrough():
    x = jnp.ones((4, 4), jnp.float32)
    assert F.matmul(x, x).dtype == jnp.float32
    h = jnp.ones((8,), HALF)
    assert F.exp(h).dtype == HALF


def test_o0_is_passthrough():
    x = jnp.ones((4, 4), jnp.float32)
    with F.active_policy(get_policy("O0")):
        assert F.matmul(x, x).dtype == jnp.float32


# --- promotion (test_promotion.py semantics) -------------------------------

@pytest.mark.parametrize("fn_name", ["multiply", "add", "divide", "arctan2"])
def test_binary_promote_matches_widest(fn_name):
    fn = getattr(F, fn_name)
    with o1():
        for xt, yt in itertools.product(DTYPES, DTYPES):
            out = fn(jnp.ones((4,), xt), jnp.ones((4,), yt))
            if xt == yt and xt != jnp.float32:
                # matching halves stay narrow (no silent fp32 upgrade)
                assert out.dtype == xt, (xt, yt)
            elif xt == jnp.float32 or yt == jnp.float32 or xt != yt:
                # widest wins; fp16+bf16 has no common half -> fp32
                assert out.dtype == jnp.float32, (xt, yt)


def test_comparison_promotes_operands():
    with o1():
        out = F.greater(jnp.ones((4,), HALF), jnp.ones((4,), jnp.float32))
        assert out.dtype == jnp.bool_  # comparison result; no dtype error


def test_sequence_cast_widest():
    with o1():
        a = jnp.ones((2, 2), HALF)
        b = jnp.ones((2, 2), jnp.float32)
        assert F.concatenate([a, b]).dtype == jnp.float32
        assert F.stack([a, a]).dtype == HALF


def test_kwargs_follow_cast_rules():
    x = jnp.ones((4, 4), jnp.float32)
    h = jnp.ones((8,), HALF)
    with o1():
        # keyword args must be cast exactly like positional ones
        assert F.matmul(x, b=x).dtype == HALF
        assert F.softmax(x=h).dtype == jnp.float32
        assert F.concatenate(arrays=[h, jnp.ones((8,), jnp.float32)]).dtype \
            == jnp.float32


def test_later_non_o1_initialize_keeps_o1_policy():
    import apex_tpu.amp as amp

    x = jnp.ones((4, 4), jnp.float32)
    amp.initialize(lambda p, a: a, {}, opt_level="O1", half_dtype=HALF)
    try:
        amp.initialize(lambda p, a: a, {}, opt_level="O2")
        assert F.matmul(x, x).dtype == HALF  # O1 policy survived
    finally:
        F.set_active_policy(None)


def test_grad_dtype_preserved_through_half_matmul():
    # test_promotion.py: x_leaf.grad.dtype == xtype — the cotangent wrt an
    # fp32 leaf must come back fp32 even when the op ran in half
    x = jnp.ones((4, 4), jnp.float32)

    def loss(x):
        with o1():
            return F.matmul(x, x).astype(jnp.float32).sum()

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.float32


# --- policy coherence (frontend.py O-level properties) ---------------------

def test_policy_properties_match_reference_table():
    o0, o1p, o2, o3 = (get_policy(l, half_dtype=jnp.float16)
                       for l in ("O0", "O1", "O2", "O3"))
    # frontend.py: O0 fp32 everything, no scaling
    assert o0.param_dtype == jnp.float32 and o0.loss_scale is None
    # O1: fp32 params, half compute, dynamic scale (fp16)
    assert o1p.param_dtype == jnp.float32
    assert o1p.compute_dtype == jnp.float16
    assert o1p.loss_scale == "dynamic"
    # O2: half params, master weights, keeps norms fp32
    assert o2.param_dtype == jnp.float16 and o2.master_weights
    assert o2.keep_norm_fp32
    # O3: pure half, no exemptions
    assert o3.param_dtype == jnp.float16 and not o3.keep_norm_fp32


def test_lists_cover_reference_categories():
    from apex_tpu.amp import lists

    # spot-pin the load-bearing classifications
    assert "matmul" in lists.HALF_FUNCS
    assert "conv_general_dilated" in lists.HALF_FUNCS
    for name in ("exp", "log", "sum", "softmax", "rsqrt"):
        assert name in lists.FLOAT_FUNCS
    for name in ("add", "multiply", "arctan2"):
        assert name in lists.PROMOTE_FUNCS
    assert "concatenate" in lists.SEQUENCE_FUNCS


# every entry of the reference registries (torch_overrides.py:7-115,
# functional_overrides.py:16-80, tensor_overrides.py:13-48), as data
_REF_TORCH = [
    "conv1d", "conv2d", "conv3d", "conv_transpose1d", "conv_transpose2d",
    "conv_transpose3d", "conv_tbc", "prelu", "addmm", "addmv", "addr",
    "matmul", "mm", "mv", "bmm", "addbmm", "baddbmm",
    "acos", "asin", "cosh", "erfinv", "exp", "expm1", "log", "log10",
    "log2", "reciprocal", "rsqrt", "sinh", "tan", "pow", "cumprod",
    "cumsum", "dist", "mean", "norm", "prod", "std", "sum", "var",
    "renorm",
    "addcdiv", "addcmul", "atan2", "cross", "bilinear", "dot", "add",
    "div", "mul", "eq", "equal", "ge", "gt", "le", "lt", "ne",
    "cat", "stack",
]
_REF_FUNCTIONAL = [
    "linear", "interpolate", "grid_sample", "softplus", "softmin",
    "log_softmax", "softmax", "gelu", "layer_norm", "group_norm",
    "local_response_norm", "normalize", "cosine_similarity",
    "poisson_nll_loss", "cosine_embedding_loss", "cross_entropy",
    "hinge_embedding_loss", "kl_div", "l1_loss", "mse_loss",
    "margin_ranking_loss", "multilabel_margin_loss",
    "multilabel_soft_margin_loss", "multi_margin_loss", "nll_loss",
    "binary_cross_entropy_with_logits", "smooth_l1_loss",
    "soft_margin_loss", "triplet_margin_loss", "ctc_loss",
    "binary_cross_entropy",
]
_REF_TENSOR = [
    "__matmul__", "__pow__", "__ipow__", "__rpow__", "cpu", "__add__",
    "__iadd__", "__radd__", "__sub__", "__isub__", "__rsub__", "__mul__",
    "__imul__", "__rmul__", "__div__", "__idiv__", "__rdiv__",
    "__truediv__", "__itruediv__", "__rtruediv__", "__eq__", "__ne__",
    "__ge__", "__gt__", "__le__", "__lt__",
]


def test_reference_map_is_complete():
    """VERDICT r2 item 8: every reference registry entry is mapped to a JAX
    op, an owning apex_tpu module, or an explicit N/A."""
    from apex_tpu.amp import lists

    all_wrapped = set(lists.HALF_FUNCS + lists.FLOAT_FUNCS
                      + lists.PROMOTE_FUNCS + lists.SEQUENCE_FUNCS)
    for entry in _REF_TORCH + _REF_FUNCTIONAL + _REF_TENSOR:
        assert entry in lists.REFERENCE_MAP, f"unmapped: {entry}"
        val = lists.REFERENCE_MAP[entry]
        if val.startswith(("N/A", "module:", "BANNED")):
            continue
        assert val in all_wrapped, f"{entry} -> {val} not in any cast list"


def test_new_float_funcs_cast_under_o1():
    x = jnp.ones((8, 8), HALF) * 0.3
    with o1():
        assert F.gelu(x).dtype == jnp.float32
        assert F.erf_inv(x).dtype == jnp.float32
        assert F.standardize(x).dtype == jnp.float32
        assert F.dot_general(
            x, x, (((1,), (0,)), ((), ()))).dtype == HALF


def test_banned_binary_cross_entropy_raises():
    with pytest.raises(RuntimeError, match="logits"):
        F.binary_cross_entropy(jnp.ones((4,)), jnp.ones((4,)))


def test_register_float_function():
    if hasattr(F, "sigmoid"):
        delattr(F, "sigmoid")
    F.register_float_function("sigmoid")
    x = jnp.ones((4,), HALF)
    with o1():
        assert F.sigmoid(x).dtype == jnp.float32
    assert F.sigmoid(x).dtype == HALF  # passthrough without a policy
    # custom callable flavor
    F.register_half_function("my_gemm", lambda a, b: a @ b)
    with o1():
        assert F.my_gemm(jnp.ones((4, 4)), jnp.ones((4, 4))).dtype == HALF
