"""amp cast-list contract tests — port of the reference's L0/run_amp
behavioral suite (test_basic_casts.py, test_promotion.py) to the
policy-scoped functional namespace."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp import functional as F
from apex_tpu.amp.policy import get_policy

HALF = jnp.bfloat16
DTYPES = [jnp.float16, jnp.bfloat16, jnp.float32]


@pytest.fixture(autouse=True)
def _no_ambient_policy():
    # amp.initialize installs a process-wide policy; isolate from any test
    # that ran it earlier
    F.set_active_policy(None)
    yield
    F.set_active_policy(None)


def o1():
    return F.active_policy(get_policy("O1", half_dtype=HALF))


# --- basic casts (test_basic_casts.py run_layer_test semantics) ------------

def test_matmul_casts_to_half_under_o1():
    x = jnp.ones((4, 4), jnp.float32)
    with o1():
        assert F.matmul(x, x).dtype == HALF
        assert F.einsum("ij,jk->ik", x, x).dtype == HALF


def test_float_funcs_cast_to_fp32_under_o1():
    x = jnp.ones((8,), HALF)
    with o1():
        assert F.exp(x).dtype == jnp.float32
        assert F.sum(x).dtype == jnp.float32
        assert F.softmax(x).dtype == jnp.float32
        assert F.linalg_norm(x).dtype == jnp.float32


def test_no_policy_is_passthrough():
    x = jnp.ones((4, 4), jnp.float32)
    assert F.matmul(x, x).dtype == jnp.float32
    h = jnp.ones((8,), HALF)
    assert F.exp(h).dtype == HALF


def test_o0_is_passthrough():
    x = jnp.ones((4, 4), jnp.float32)
    with F.active_policy(get_policy("O0")):
        assert F.matmul(x, x).dtype == jnp.float32


# --- promotion (test_promotion.py semantics) -------------------------------

@pytest.mark.parametrize("fn_name", ["multiply", "add", "divide", "arctan2"])
def test_binary_promote_matches_widest(fn_name):
    fn = getattr(F, fn_name)
    with o1():
        for xt, yt in itertools.product(DTYPES, DTYPES):
            out = fn(jnp.ones((4,), xt), jnp.ones((4,), yt))
            if xt == yt and xt != jnp.float32:
                # matching halves stay narrow (no silent fp32 upgrade)
                assert out.dtype == xt, (xt, yt)
            elif xt == jnp.float32 or yt == jnp.float32 or xt != yt:
                # widest wins; fp16+bf16 has no common half -> fp32
                assert out.dtype == jnp.float32, (xt, yt)


def test_comparison_promotes_operands():
    with o1():
        out = F.greater(jnp.ones((4,), HALF), jnp.ones((4,), jnp.float32))
        assert out.dtype == jnp.bool_  # comparison result; no dtype error


def test_sequence_cast_widest():
    with o1():
        a = jnp.ones((2, 2), HALF)
        b = jnp.ones((2, 2), jnp.float32)
        assert F.concatenate([a, b]).dtype == jnp.float32
        assert F.stack([a, a]).dtype == HALF


def test_kwargs_follow_cast_rules():
    x = jnp.ones((4, 4), jnp.float32)
    h = jnp.ones((8,), HALF)
    with o1():
        # keyword args must be cast exactly like positional ones
        assert F.matmul(x, b=x).dtype == HALF
        assert F.softmax(x=h).dtype == jnp.float32
        assert F.concatenate(arrays=[h, jnp.ones((8,), jnp.float32)]).dtype \
            == jnp.float32


def test_later_non_o1_initialize_keeps_o1_policy():
    import apex_tpu.amp as amp

    x = jnp.ones((4, 4), jnp.float32)
    amp.initialize(lambda p, a: a, {}, opt_level="O1", half_dtype=HALF)
    try:
        amp.initialize(lambda p, a: a, {}, opt_level="O2")
        assert F.matmul(x, x).dtype == HALF  # O1 policy survived
    finally:
        F.set_active_policy(None)


def test_grad_dtype_preserved_through_half_matmul():
    # test_promotion.py: x_leaf.grad.dtype == xtype — the cotangent wrt an
    # fp32 leaf must come back fp32 even when the op ran in half
    x = jnp.ones((4, 4), jnp.float32)

    def loss(x):
        with o1():
            return F.matmul(x, x).astype(jnp.float32).sum()

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.float32


# --- policy coherence (frontend.py O-level properties) ---------------------

def test_policy_properties_match_reference_table():
    o0, o1p, o2, o3 = (get_policy(l, half_dtype=jnp.float16)
                       for l in ("O0", "O1", "O2", "O3"))
    # frontend.py: O0 fp32 everything, no scaling
    assert o0.param_dtype == jnp.float32 and o0.loss_scale is None
    # O1: fp32 params, half compute, dynamic scale (fp16)
    assert o1p.param_dtype == jnp.float32
    assert o1p.compute_dtype == jnp.float16
    assert o1p.loss_scale == "dynamic"
    # O2: half params, master weights, keeps norms fp32
    assert o2.param_dtype == jnp.float16 and o2.master_weights
    assert o2.keep_norm_fp32
    # O3: pure half, no exemptions
    assert o3.param_dtype == jnp.float16 and not o3.keep_norm_fp32


def test_lists_cover_reference_categories():
    from apex_tpu.amp import lists

    # spot-pin the load-bearing classifications
    assert "matmul" in lists.HALF_FUNCS
    assert "conv_general_dilated" in lists.HALF_FUNCS
    for name in ("exp", "log", "sum", "softmax", "rsqrt"):
        assert name in lists.FLOAT_FUNCS
    for name in ("add", "multiply", "arctan2"):
        assert name in lists.PROMOTE_FUNCS
    assert "concatenate" in lists.SEQUENCE_FUNCS
