"""Smoke tests for the example drivers added in r3 (dcgan, bert).

Each runs the real script in a subprocess on the virtual CPU mesh — the
same way a user would — and checks its own convergence assertions pass.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

# extend (not replace) the environment: a from-scratch dict hardcodes
# HOME/PATH and drops TMPDIR/proxies for non-root users.  PYTHONPATH is
# overridden on purpose — it removes the axon sitecustomize so the
# subprocess gets a plain CPU jax.
ENV = {**os.environ,
       "JAX_PLATFORMS": "cpu",
       "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
       "PYTHONPATH": str(REPO)}


@pytest.mark.slow   # ~21 s: two-optimizer fp16 scaling keeps tier-1
# witnesses in test_amp.py; the dcgan driver itself is smoke-only
def test_dcgan_amp_two_optimizers():
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "dcgan" / "main_amp.py"),
         "--steps", "4", "--batch", "8", "--half", "fp16",
         "--opt-level", "O2"],
        capture_output=True, text=True, timeout=600, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "dcgan amp OK" in out.stdout


def test_bert_pretrain_dp():
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "bert" / "pretrain.py"),
         "--steps", "6", "--layers", "2", "--hidden", "64", "--heads", "2",
         "--vocab", "256", "--seq", "64", "--batch", "8"],
        capture_output=True, text=True, timeout=600, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "bert pretrain OK: dp=8" in out.stdout


@pytest.mark.slow   # ~11 s: tier-1 keeps test_llama_pretrain_3d_tp_pp_dp,
# which drives the same pretrain.py with tp AND pp AND dp axes live — the
# 2-D tp×dp mesh is a strict subset of that witness
def test_llama_pretrain_tp_dp():
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "llama" / "pretrain.py"),
         "--steps", "6", "--layers", "2", "--hidden", "64", "--heads", "4",
         "--kv-heads", "2", "--ffn", "128", "--vocab", "256", "--seq", "64",
         "--batch", "8", "--tp", "2"],
        capture_output=True, text=True, timeout=600, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "llama pretrain OK: dp=4 tp=2" in out.stdout


def _make_fake_imagefolder(root, classes=3, per_class=6, size=40):
    from PIL import Image
    rng = __import__("numpy").random.default_rng(0)
    for c in range(classes):
        d = root / f"class_{c}"
        d.mkdir(parents=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size, size, 3), dtype="uint8")
            Image.fromarray(arr).save(d / f"img_{i}.jpg")


@pytest.mark.slow   # ~13 s: the data-path machinery itself (ImageFolder,
# PIL decode, augment, batching, worker pool) keeps its in-process tier-1
# witnesses (test_batch_iterator_workers_matches_serial,
# test_prefetch_loader_propagates_decode_errors); this subprocess rider
# re-proves only the example's --data-dir flag wiring
def test_imagenet_real_data_path(tmp_path):
    """--data-dir trains on a real image tree (VERDICT r3 item 8): PIL
    decode + augment + prefetch feeding the amp/DDP/FusedSGD step."""
    _make_fake_imagefolder(tmp_path / "train")
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "imagenet" / "main.py"),
         "--arch", "resnet10", "--image-size", "32", "--batch-size", "8",
         "--steps", "6", "--data-dir", str(tmp_path / "train")],
        capture_output=True, text=True, timeout=600, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "data: 18 images, 3 classes" in out.stdout
    assert "OK" in out.stdout


def test_prefetch_loader_propagates_decode_errors(tmp_path):
    """A corrupt image must surface as the decode error itself, not as a
    bare StopIteration indistinguishable from clean end-of-data."""
    import pytest

    sys.path.insert(0, str(REPO / "examples" / "imagenet"))
    from data import ImageFolder, PrefetchLoader, batch_iterator

    _make_fake_imagefolder(tmp_path / "t", classes=2, per_class=3)
    (tmp_path / "t" / "class_0" / "img_0.jpg").write_bytes(b"not a jpeg")
    ds = ImageFolder(str(tmp_path / "t"))
    loader = PrefetchLoader(batch_iterator(ds, 6, 32, train=False, epochs=1))
    with pytest.raises(Exception) as ei:
        for _ in range(10):
            next(loader)
    assert not isinstance(ei.value, StopIteration), (
        "decode failure was swallowed into end-of-data")


def test_llama_pretrain_3d_tp_pp_dp():
    """BASELINE.md row 5 component set: Llama over dp x pp x tp with the
    1F1B schedule (VERDICT r3 item 5)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "llama" / "pretrain.py"),
         "--steps", "6", "--layers", "4", "--hidden", "64", "--heads", "4",
         "--kv-heads", "2", "--ffn", "128", "--vocab", "256", "--seq", "32",
         "--tp", "2", "--pp", "2", "--micro-batch", "2", "--n-micro", "4"],
        capture_output=True, text=True, timeout=600, env=ENV)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "llama pretrain OK: dp=2 pp=2 tp=2" in out.stdout


def test_batch_iterator_workers_matches_serial(tmp_path):
    """workers>0 fans decode across a thread pool (the reference
    DataLoader's workers knob, PERF_NOTES r5 input-pipeline section):
    same batch shapes/labels and the same images modulo augmentation
    randomness; eval mode (deterministic) must match exactly."""
    import numpy as np

    sys.path.insert(0, str(REPO / "examples" / "imagenet"))
    from data import ImageFolder, batch_iterator

    _make_fake_imagefolder(tmp_path / "t", classes=2, per_class=4)
    ds = ImageFolder(str(tmp_path / "t"))
    serial = list(batch_iterator(ds, 4, 32, train=False, epochs=1))
    pooled = list(batch_iterator(ds, 4, 32, train=False, epochs=1,
                                 workers=4))
    assert len(serial) == len(pooled) == 2
    for (si, sl), (pi, pl) in zip(serial, pooled):
        np.testing.assert_array_equal(sl, pl)
        np.testing.assert_allclose(si, pi, rtol=1e-6)
    # train mode with workers: just shape/dtype sanity (augmentation rng
    # streams differ from the serial path by design)
    imgs, labels = next(batch_iterator(ds, 4, 32, train=True, workers=2))
    assert imgs.shape == (4, 32, 32, 3) and labels.shape == (4,)
