"""Paged KV cache (ISSUE 11): block pool + block tables with zero-copy
refcounted prefix sharing.

THE acceptance runs: paged-engine greedy streams are **bit-identical**
(exact f32 logits per step) to the dense engine across chunked prefill,
batched decode, speculative verification, and prefix reuse — including
the multi-stream scheduler interleaving where a routing bug would first
show (each decode lane must write through its OWN slot's table row, the
regression this suite pins).  Prefix-cache hits on a paged engine
perform ZERO K/V copies, witnessed by compile counts: the restore and
region-read programs never compile, and CoW only compiles once a write
actually targets a shared block.

Plus the block-table edge cases the issue names: a table exactly full
at ``max_len`` (including ``max_len`` not a block multiple), CoW on a
shared tail block with both sharers still decoding (bit-isolation both
ways), refcount-pinned blocks surviving a tight-budget eviction pass,
and allocator exhaustion raising instead of clamping.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import serving as sv
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.serving.paged_kv_cache import (
    BlockPoolExhausted,
    PagedCacheManager,
    PagedKVCache,
    blocks_per_slot,
    decode_view,
    init_paged_cache,
    paged_append,
    paged_prefill_write,
)
from apex_tpu.utils.compat import compile_count

# the serving suite's GQA config (kv_heads < heads)
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def _prompt(seed=0, n=5):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, CFG.vocab_size, n)]


class _EventTap:
    """Capture emit_event kinds (and payloads) for a with-block."""

    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


# ---------------------------------------------------------------------------
# host allocator: refcounts, LIFO determinism, CoW planning
# ---------------------------------------------------------------------------


def test_manager_alloc_release_refcount_lifo():
    mgr = PagedCacheManager(slots=2, max_len=32, block_size=8,
                            num_blocks=9)        # null + 8
    assert mgr.free_blocks == 8 and mgr.used_blocks == 0
    assert mgr.utilization == 0.0
    # growth allocates deterministically (LIFO free list pops 1, 2, ...)
    assert mgr.ensure(0, 0, 20) == []            # 3 fresh blocks, no CoW
    assert mgr.slot_block_ids(0) == [1, 2, 3]
    assert mgr.used_blocks == 3 and mgr.refcount(2) == 1
    assert mgr.consume_dirty() and not mgr.consume_dirty()
    # within-span re-ensure: nothing allocated, nothing dirty
    assert mgr.ensure(0, 8, 16) == []
    assert not mgr.consume_dirty()
    # release frees in reverse token order; LIFO reuse is replayable
    assert mgr.release(0) == 3
    assert mgr.free_blocks == 8
    mgr.ensure(1, 0, 8)
    assert mgr.slot_block_ids(1) == [3]          # last freed, first reused
    assert mgr.stats()["allocated_total"] == 4
    assert mgr.stats()["freed_total"] == 3


def test_manager_alias_fork_cow_planning():
    mgr = PagedCacheManager(slots=3, max_len=32, block_size=8,
                            num_blocks=9)
    mgr.ensure(0, 0, 20)                         # blocks 1..3, tail partial
    shared = mgr.slot_block_ids(0)
    # aliasing the whole chain: every block gains a reference
    mgr.alias(1, shared, tokens=20)
    assert [mgr.refcount(b) for b in shared] == [2, 2, 2]
    assert mgr.aliased_total == 3
    # a write into slot 1's shared tail block must CoW exactly it
    pairs = mgr.ensure(1, 20, 21)
    assert len(pairs) == 1 and pairs[0][0] == shared[2]
    new = pairs[0][1]
    assert mgr.slot_block_ids(1) == shared[:2] + [new]
    assert mgr.refcount(shared[2]) == 1 and mgr.refcount(new) == 1
    assert mgr.cow_total == 1
    # releasing the original owner keeps the still-shared prefix alive
    assert mgr.release(0) == 1                   # only the un-CoW'd tail
    assert [mgr.refcount(b) for b in shared[:2]] == [1, 1]
    # fork shares every block of a live slot (no aliased_total noise)
    before = mgr.aliased_total
    mgr.fork(1, 2)
    assert mgr.slot_block_ids(2) == mgr.slot_block_ids(1)
    assert mgr.aliased_total == before
    assert mgr.refcount(new) == 2


def test_manager_validation_and_guards():
    mgr = PagedCacheManager(slots=2, max_len=16, block_size=8,
                            num_blocks=5)
    with pytest.raises(ValueError):              # ref of a free block
        mgr.ref([1])
    with pytest.raises(ValueError):              # deref must pair
        mgr.deref([1])
    mgr.ensure(0, 0, 16)
    with pytest.raises(ValueError):              # alias into occupied
        mgr.alias(0, [1], tokens=8)
    with pytest.raises(ValueError):              # tokens not coverable
        mgr.alias(1, [1], tokens=9)
    with pytest.raises(ValueError):              # table overflow
        mgr.alias(1, [1, 2, 1], tokens=17)
    with pytest.raises(ValueError):              # span outside capacity
        mgr.ensure(0, 8, 17)
    with pytest.raises(ValueError):              # fork of empty slot
        mgr.fork(1, 0)
    with pytest.raises(ValueError):
        PagedCacheManager(slots=1, max_len=8, block_size=16, num_blocks=3)
    with pytest.raises(ValueError):
        PagedCacheManager(slots=1, max_len=8, block_size=4, num_blocks=1)
    with pytest.raises(ValueError):
        sv.PagedCacheConfig(block_size=0)
    with pytest.raises(ValueError):
        sv.PagedCacheConfig(num_blocks=1)


def test_allocator_exhaustion_raises_never_clamps():
    mgr = PagedCacheManager(slots=2, max_len=16, block_size=8,
                            num_blocks=3)        # null + 2
    mgr.ensure(0, 0, 16)                         # both blocks gone
    with pytest.raises(BlockPoolExhausted):
        mgr.ensure(1, 0, 8)
    # the failed ensure must not have corrupted slot 1's table
    assert mgr.slot_block_ids(1) == []
    # a reclaim hook that frees nothing still raises; one that frees
    # satisfies the allocation
    calls = []
    mgr.reclaim = lambda n: calls.append(n) or 0
    with pytest.raises(BlockPoolExhausted):
        mgr.ensure(1, 0, 8)
    assert calls == [1]
    mgr.reclaim = lambda n: mgr.release(0)
    assert mgr.ensure(1, 0, 8) == []
    assert mgr.slot_block_ids(1) != []


# ---------------------------------------------------------------------------
# device ops: per-slot routing + drop-safe scatters (unit level)
# ---------------------------------------------------------------------------


def _tiny_cache(slots=2, max_len=16, block_size=8, num_blocks=9):
    return init_paged_cache(CFG, slots=slots, max_len=max_len,
                            block_size=block_size, num_blocks=num_blocks)


def test_append_routes_each_lane_through_its_own_table():
    """REGRESSION: the batched append must take the table DIAGONAL —
    row i through slot i's table.  The outer-product form (plain
    ``take`` over the last axis) scattered every lane's token through
    every slot's table at its block offset, corrupting any neighbor
    whose table had an entry at the same index: first visible as a
    one-bit stream divergence with >= 2 concurrently decoding
    scheduler streams."""
    cache = _tiny_cache()
    mgr = PagedCacheManager(slots=2, max_len=16, block_size=8,
                            num_blocks=9)
    mgr.ensure(0, 0, 9)                          # slot 0: blocks 1, 2
    mgr.ensure(1, 0, 9)                          # slot 1: blocks 3, 4
    cache = dataclasses.replace(
        cache, tables=jnp.asarray(mgr.table_snapshot()))
    hd = CFG.hidden_size // CFG.num_attention_heads
    k_tok = jnp.stack([jnp.full((CFG.kv_heads, hd), 7.0),
                       jnp.full((CFG.kv_heads, hd), 9.0)])
    # both lanes append at position 8 — block index 1 in BOTH tables
    cache = paged_append(cache, 0, k_tok, k_tok,
                         jnp.asarray([8, 8], jnp.int32))
    pool = np.asarray(cache.k[0])                # [nblk, bs, kvh, hd]
    assert (pool[2, 0] == 7.0).all()             # slot 0 -> its block 2
    assert (pool[4, 0] == 9.0).all()             # slot 1 -> its block 4
    assert (pool[2, 0] != 9.0).all() and (pool[4, 0] != 7.0).all()
    # inactive sentinel (-1) and past-capacity rows are DROPPED
    cache = paged_append(cache, 0, k_tok * 0 + 5.0, k_tok,
                         jnp.asarray([-1, 16], jnp.int32))
    pool = np.asarray(cache.k[0])
    assert not (pool == 5.0).any()


def test_prefill_write_drops_padding_past_frontier():
    cache = _tiny_cache()
    mgr = PagedCacheManager(slots=2, max_len=16, block_size=8,
                            num_blocks=9)
    mgr.ensure(0, 0, 5)                          # one block allocated
    cache = dataclasses.replace(
        cache, tables=jnp.asarray(mgr.table_snapshot()))
    hd = CFG.hidden_size // CFG.num_attention_heads
    chunk = jnp.full((8, CFG.kv_heads, hd), 3.0)  # bucket-padded chunk
    cache = paged_prefill_write(cache, 0, 0, chunk, chunk, start=0)
    pool = np.asarray(cache.k[0])
    assert (pool[1] == 3.0).all()                # the allocated block
    assert (pool[0] == 0.0).all()                # null block never written
    assert (pool[2:] == 0.0).all()               # nothing else touched
    # rows past the frontier (table entry null) drop silently: writing
    # at start=8 with no second block allocated lands nowhere
    cache = paged_prefill_write(cache, 0, 0, chunk * 0 + 4.0, chunk,
                                start=8)
    assert not (np.asarray(cache.k[0]) == 4.0).any()


def test_gather_view_slices_to_max_len_when_not_block_multiple():
    # max_len 20 with block_size 8 -> 3 blocks cover 24 rows; the view
    # must slice back to exactly 20 so reduction extents match dense
    cache = init_paged_cache(CFG, slots=2, max_len=20, block_size=8,
                             num_blocks=9)
    assert cache.blocks_per_slot == blocks_per_slot(20, 8) == 3
    k, v = decode_view(cache, 0)
    assert k.shape == (2, 20, CFG.kv_heads,
                       CFG.hidden_size // CFG.num_attention_heads)
    assert v.shape == k.shape


# ---------------------------------------------------------------------------
# engine parity: paged == dense, bit for bit
# ---------------------------------------------------------------------------


def _engines(model, params, *, max_len=MAX, block_size=16, slots=2,
             num_blocks=None, prefill_len=16):
    dense = sv.DecodeEngine(model, params, slots=slots, max_len=max_len,
                            prefill_len=prefill_len)
    paged = sv.DecodeEngine(
        model, params, slots=slots, max_len=max_len,
        prefill_len=prefill_len,
        paged=sv.PagedCacheConfig(block_size=block_size,
                                  num_blocks=num_blocks))
    return dense, paged


@pytest.mark.parametrize("block_size", [16, 12])
def test_engine_prefill_decode_bit_identical(model, params, block_size):
    """Chunked prefill + 12 greedy decode steps: every f32 logit vector
    identical between the dense and paged engines — including a
    block_size that does NOT divide max_len (the gather-slice edge)."""
    dense, paged = _engines(model, params, block_size=block_size)
    prompt = _prompt(seed=1, n=42)               # 3 chunks, bucketed tail
    ld = dense.prefill(0, prompt)
    lp = paged.prefill(0, prompt)
    assert np.array_equal(np.asarray(ld), np.asarray(lp)), (
        "paged prefill logits diverged from dense")
    for step in range(12):
        nxt = int(jnp.argmax(ld))
        ld = dense.decode(np.array([nxt, 0], np.int32),
                          np.array([True, False]))[0]
        lp = paged.decode(np.array([nxt, 0], np.int32),
                          np.array([True, False]))[0]
        assert np.array_equal(np.asarray(ld), np.asarray(lp)), (
            f"paged decode diverged from dense at step {step}")
    assert paged.decode_compiles() == 1
    assert paged.prefill_compiles() <= len(paged.prefill_buckets)


@pytest.mark.slow   # ~7 s: tier-1 keeps the dense spec-verify parity
# witnesses in test_serving.py and the sharded one in test_serving_tp.py
def test_engine_verify_draft_bit_identical(model, params):
    dense, paged = _engines(model, params)
    prompt = _prompt(seed=2, n=30)
    ld = dense.prefill(0, prompt)
    lp = paged.prefill(0, prompt)
    pending = int(jnp.argmax(ld))
    draft = _prompt(seed=3, n=4)
    draft[0] = pending                           # guarantee >= 0 accepts
    ad, gd, rd = dense.verify_draft(0, [pending] + draft)
    ap, gp, rp = paged.verify_draft(0, [pending] + draft)
    assert ad == ap and np.array_equal(gd, gp)
    assert np.array_equal(np.asarray(rd), np.asarray(rp))
    assert dense.lengths()[0] == paged.lengths()[0]
    # post-rollback decode still agrees (the rolled-back rows are
    # unreadable on both layouts)
    tok = int(gd[ad])
    ld = dense.decode(np.array([tok, 0], np.int32),
                      np.array([True, False]))[0]
    lp = paged.decode(np.array([tok, 0], np.int32),
                      np.array([True, False]))[0]
    assert np.array_equal(np.asarray(ld), np.asarray(lp))


@pytest.mark.slow   # ~12 s: tier-1 keeps the engine-level paged==dense
# bit-identity witnesses (test_engine_prefill_decode_bit_identical[12/16])
# plus the paged scheduler streams driven by the policy/fleet/rollout suites
def test_scheduler_streams_bit_identical_multi_stream(model, params):
    """THE scheduler acceptance run: 4 shared-prefix prompts through
    dense, paged, paged+speculation, and paged+prefix-caching
    schedulers — identical token streams everywhere, with prefill and
    decode interleaving across >= 2 concurrently decoding slots (the
    regime that exposes any cross-slot table routing bug)."""
    shared = _prompt(seed=4, n=40)
    prompts = [shared + _prompt(seed=100 + i, n=8) for i in range(4)]

    def run(paged, *, spec=False, prefix=False):
        eng = sv.DecodeEngine(
            model, params, slots=4, max_len=MAX, prefill_len=16,
            paged=sv.PagedCacheConfig(block_size=16) if paged else None)
        sched = sv.ContinuousBatchingScheduler(
            eng, log_interval=10 ** 9,
            speculation=sv.SpeculationConfig() if spec else None,
            prefix_caching=sv.PrefixCacheConfig() if prefix else None)
        for i, p in enumerate(prompts):
            sched.submit(sv.Request(f"r{i}", p, max_new_tokens=6))
        res = sched.run()
        return eng, sched, [res[f"r{i}"].tokens for i in range(4)]

    _, _, want = run(False)
    _, _, got = run(True)
    assert got == want, "paged scheduler streams diverged from dense"
    _, _, got = run(True, spec=True)
    assert got == want, "paged+speculation streams diverged"
    eng, sched, got = run(True, prefix=True)
    assert got == want, "paged+prefix streams diverged"
    # warm round: same prompts re-admit via zero-copy aliasing and
    # still match the dense stream bit for bit
    for i, p in enumerate(prompts):
        sched.submit(sv.Request(f"w{i}", p, max_new_tokens=6))
    res = sched.run()
    assert [res[f"w{i}"].tokens for i in range(4)] == want, (
        "warm aliased streams diverged")
    assert eng.block_stats()["aliased_total"] > 0
    # every stream drained: only the prefix cache's references remain
    assert eng.block_pool.used_blocks == len(sched.prefix_cache)


def test_table_exactly_full_at_max_len(model, params):
    """A stream may fill its table to exactly ``max_len`` (every block
    allocated, the last row written) — parity holds at the boundary and
    the overflow append still raises instead of clamping.  max_len 24
    with block_size 16 also pins the not-a-multiple table extent."""
    dense, paged = _engines(model, params, max_len=24, block_size=16,
                            prefill_len=8)
    prompt = _prompt(seed=5, n=20)
    ld = dense.prefill(0, prompt)
    lp = paged.prefill(0, prompt)
    toks = []
    for step in range(4):                        # 20 + 4 appends == 24
        nxt = int(jnp.argmax(ld))
        toks.append(nxt)
        ld = dense.decode(np.array([nxt, 0], np.int32),
                          np.array([True, False]))[0]
        lp = paged.decode(np.array([nxt, 0], np.int32),
                          np.array([True, False]))[0]
        assert np.array_equal(np.asarray(ld), np.asarray(lp)), (
            f"diverged at step {step} while filling to max_len")
    assert dense.lengths()[0] == paged.lengths()[0] == 24
    assert paged.block_pool.slot_block_ids(0) != []
    assert len(paged.block_pool.slot_block_ids(0)) \
        == blocks_per_slot(24, 16)
    for eng in (dense, paged):
        with pytest.raises(ValueError):          # full is full
            eng.decode(np.array([toks[-1], 0], np.int32),
                       np.array([True, False]))
    # release returns every block of the full table
    paged.release(0)
    assert paged.block_pool.used_blocks == 0


def test_cow_shared_tail_bit_isolation_both_ways(model, params):
    """Fork a live stream mid-block and keep BOTH sharers decoding
    different continuations in the same batched step: the first write
    into the shared tail block copies it, each stream's logits stay
    bit-identical to a solo dense run of its own continuation, and
    exactly one CoW (one compile) is paid."""
    prompt = _prompt(seed=6, n=20)               # tail block 20..31 shared
    _, paged = _engines(model, params, slots=2, block_size=16)
    lp = paged.prefill(0, prompt)
    first = int(jnp.argmax(lp))
    paged.fork_slot(0, 1)
    assert paged.cow_compiles() == 0             # sharing alone is free
    conts = [first, (first + 1) % CFG.vocab_size]

    # solo dense references, one per continuation
    refs = []
    for cont in conts:
        eng = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                              prefill_len=16)
        logits = eng.prefill(0, prompt)
        steps = []
        tok = cont
        for _ in range(8):
            logits = eng.decode(np.array([tok], np.int32),
                                np.array([True]))[0]
            steps.append(np.asarray(logits))
            tok = int(jnp.argmax(logits))
        refs.append(steps)

    with _EventTap() as tap:
        toks = list(conts)
        for step in range(8):
            logits = paged.decode(np.array(toks, np.int32),
                                  np.array([True, True]))
            for slot in (0, 1):
                assert np.array_equal(np.asarray(logits[slot]),
                                      refs[slot][step]), (
                    f"sharer {slot} diverged from its solo run at "
                    f"step {step} — CoW bit-isolation broken")
            toks = [int(jnp.argmax(logits[s])) for s in (0, 1)]
    # exactly one block copied: the first writer CoW'd the tail, the
    # second then owned the original exclusively
    assert paged.block_stats()["cow_total"] == 1
    assert paged.cow_compiles() == 1
    assert sum(e["blocks"] for e in tap.of("serving_block_cow")) == 1


def test_refcount_pinned_blocks_survive_tight_eviction(model, params):
    """An eviction pass under a tight block budget must free ONLY
    unpinned, childless entries: pinned chains (a live prefill's) and
    blocks still shared by slots survive, and the pass reports the
    honest freed count."""
    _, paged = _engines(model, params, slots=2, block_size=16)
    mgr = paged.block_pool
    sched = sv.ContinuousBatchingScheduler(
        paged, log_interval=10 ** 9,
        prefix_caching=sv.PrefixCacheConfig())
    pc = sched.prefix_cache
    sched.submit(sv.Request("a", _prompt(seed=7, n=40), max_new_tokens=2))
    sched.run()
    assert len(pc) == 2                          # two whole shared blocks
    entries = list(pc._entries.values())
    # pin one entry (a live prefill would); its block must survive any
    # reclaim pressure while the unpinned sibling frees
    pc.acquire([entries[1]])
    assert pc.evictable_blocks() == 0            # [0] parents [1]: chained
    freed = pc.evict_blocks(2)
    assert freed == 0                            # nothing legally freeable
    assert entries[0].chain in pc and entries[1].chain in pc
    pc.release([entries[1]])
    # now the leaf is evictable but its parent still is not
    assert pc.evictable_blocks() == 1
    freed = pc.evict_blocks(2)
    assert freed == 2                            # leaf, then freed parent
    assert len(pc) == 0 and mgr.used_blocks == 0


def test_pool_exhaustion_reclaims_prefix_then_raises(model, params):
    """The engine's allocator consults the prefix cache exactly once
    under pressure: cached-but-idle blocks are evicted to satisfy the
    allocation; with nothing reclaimable the error is loud — and no
    stream's table was harmed."""
    # pool of 5 usable blocks, slots 2, max_len 48 (3 blocks/slot)
    _, paged = _engines(model, params, max_len=48, block_size=16,
                        slots=2, num_blocks=6, prefill_len=16)
    sched = sv.ContinuousBatchingScheduler(
        paged, log_interval=10 ** 9,
        prefix_caching=sv.PrefixCacheConfig())
    sched.submit(sv.Request("a", _prompt(seed=8, n=33), max_new_tokens=2))
    sched.run()
    assert len(sched.prefix_cache) == 2          # 2 blocks cached
    assert paged.block_pool.used_blocks == 2
    # a fresh 3-block prompt fits only if the cache gives blocks back
    with _EventTap():
        sched.submit(sv.Request("b", _prompt(seed=9, n=33),
                                max_new_tokens=2))
        sched.run()
    assert paged.block_pool.free_blocks >= 1
    # exhaustion with nothing evictable: the reclaim hook drains the
    # prefix cache during these prefills, then the boundary-crossing
    # decode append finds a truly empty pool and raises
    paged.reset()
    paged.prefill(0, _prompt(seed=10, n=48))     # 3 of 5 blocks
    paged.prefill(1, _prompt(seed=11, n=32))     # 5 of 5 (block-aligned)
    assert len(sched.prefix_cache) == 0          # reclaim drained it
    with pytest.raises(BlockPoolExhausted):
        paged.decode(np.array([1, 1], np.int32),
                     np.array([False, True]))    # slot 1 needs block 3
    # the failed step corrupted nothing: slot tables intact, and after
    # releasing slot 0 the same step succeeds
    assert len(paged.block_pool.slot_block_ids(1)) == 2
    paged.release(0)
    paged.decode(np.array([1, 1], np.int32), np.array([False, True]))
    assert paged.lengths()[1] == 33


def test_scheduler_admission_prices_blocks(model, params):
    """Paged admission holds a request back while its WORST-CASE
    footprint (prompt + decode growth) cannot be covered by free +
    evictable blocks (instead of grabbing a free slot and dying at
    allocation), and admits it once live streams drain.  Oversized
    requests are rejected at submit."""
    _, paged = _engines(model, params, max_len=64, block_size=16,
                        slots=4, num_blocks=7, prefill_len=16)
    sched = sv.ContinuousBatchingScheduler(paged, log_interval=10 ** 9)
    with pytest.raises(ValueError):              # > whole pool: reject
        sched.submit(sv.Request("big", _prompt(seed=12, n=64),
                                max_new_tokens=48))
    sched.submit(sv.Request("a", _prompt(seed=13, n=48),
                            max_new_tokens=4))   # 51 rows: 4 blocks
    sched.submit(sv.Request("b", _prompt(seed=14, n=64),
                            max_new_tokens=1))   # 4 > the 2 unreserved:
    #                                              waits for a to drain
    seen_concurrent = 0
    for _ in range(60):
        sched.step()
        seen_concurrent = max(seen_concurrent, sched.active_count)
        if not (sched.queue_depth or sched.active_count):
            break
    res = sched.results
    assert set(res) == {"a", "b"}                # both served...
    assert seen_concurrent == 1                  # ...never concurrently
    # a roomier pool admits both at once (the held-back witness), and
    # the serialized streams equal the concurrent ones bit for bit
    _, roomy = _engines(model, params, max_len=64, block_size=16,
                        slots=4, prefill_len=16)
    sched2 = sv.ContinuousBatchingScheduler(roomy, log_interval=10 ** 9)
    sched2.submit(sv.Request("a", _prompt(seed=13, n=48),
                             max_new_tokens=4))
    sched2.submit(sv.Request("b", _prompt(seed=14, n=64),
                             max_new_tokens=1))
    for _ in range(4):
        sched2.step()
        if sched2.active_count == 2:
            break
    assert sched2.active_count == 2
    res2 = sched2.run()
    assert [res2[r].tokens for r in ("a", "b")] \
        == [res[r].tokens for r in ("a", "b")]


def test_admission_prices_decode_growth_not_just_prompt(model, params):
    """THE mid-decode exhaustion regression: four 2-prompt-block streams
    whose decode growth needs a 3rd block each (12 worst-case blocks)
    on a 9-block pool.  Pricing prompts alone admits all four and the
    pool exhausts when every stream crosses the block boundary
    mid-decode — an uncatchable BlockPoolExhausted that loses every
    in-flight stream.  Pricing the full footprint holds the 4th stream
    back (backpressure, not a crash) and every stream completes,
    bit-identical to the dense run."""
    prompts = [_prompt(seed=200 + i, n=17) for i in range(4)]

    def run(eng):
        sched = sv.ContinuousBatchingScheduler(eng, log_interval=10 ** 9)
        for i, p in enumerate(prompts):
            sched.submit(sv.Request(f"g{i}", p, max_new_tokens=20))
        peak = 0
        for _ in range(400):
            sched.step()
            peak = max(peak, sched.active_count)
            if not (sched.queue_depth or sched.active_count):
                break
        return [sched.results[f"g{i}"].tokens for i in range(4)], peak

    dense = sv.DecodeEngine(model, params, slots=4, max_len=64,
                            prefill_len=16)
    want, _ = run(dense)
    _, paged = _engines(model, params, max_len=64, block_size=16,
                        slots=4, num_blocks=10, prefill_len=16)
    got, peak = run(paged)
    assert got == want, "held-back streams diverged from dense"
    # the 4th stream waited: 3 x 3 reserved blocks saturate the 9-block
    # pool (prompt-only pricing would have admitted all 4 — and died)
    assert peak == 3
    assert paged.block_pool.used_blocks == 0     # clean drain


def test_scheduler_close_releases_cache_blocks_and_reclaim_hook(
        model, params):
    """close() on a caching paged scheduler derefs every cached pool
    block and unhooks the allocator's reclaim callback — abandoning the
    cache instead would pin its blocks forever and leave the engine
    reclaiming into a dead store.  A successor caching scheduler over
    the same engine starts from an empty pool and replays the same
    streams; close() with work in flight refuses."""
    _, paged = _engines(model, params, slots=2, block_size=16)
    prompt = _prompt(seed=21, n=40)

    def fleet():
        sched = sv.ContinuousBatchingScheduler(
            paged, log_interval=10 ** 9,
            prefix_caching=sv.PrefixCacheConfig())
        sched.submit(sv.Request("a", prompt, max_new_tokens=3))
        return sched, sched.run()["a"].tokens

    sched, want = fleet()
    assert len(sched.prefix_cache) == 2          # two whole blocks cached
    assert paged.block_pool.used_blocks == 2     # ...holding pool refs
    assert paged.block_pool.reclaim is not None
    sched.close()
    assert len(sched.prefix_cache) == 0
    assert paged.block_pool.used_blocks == 0     # refs released
    assert paged.block_pool.reclaim is None      # hook unwired
    sched2, got = fleet()                        # successor: clean start
    assert got == want
    sched2.close()
    assert paged.block_pool.used_blocks == 0
    sched3 = sv.ContinuousBatchingScheduler(
        paged, log_interval=10 ** 9,
        prefix_caching=sv.PrefixCacheConfig())
    sched3.submit(sv.Request("q", prompt, max_new_tokens=1))
    with pytest.raises(RuntimeError):
        sched3.close()                           # queued work: refuse
    sched3.run()
    # closing an OLDER scheduler must not unhook a newer one's reclaim
    # callback — only the hook it installed itself
    sched4 = sv.ContinuousBatchingScheduler(
        paged, log_interval=10 ** 9,
        prefix_caching=sv.PrefixCacheConfig())
    sched3.close()
    assert paged.block_pool.reclaim is not None  # sched4's hook survives
    sched4.close()
    assert paged.block_pool.reclaim is None


# ---------------------------------------------------------------------------
# zero-copy witness + events/metrics + default-off identity
# ---------------------------------------------------------------------------


def test_prefix_hit_zero_copy_dispatch_witness(model, params):
    """A paged prefix hit moves NO K/V: the restore program and the
    region-read program never compile (the whole capture/restore
    dispatch family is gone), CoW never compiles while nothing writes
    into shared tails before the suffix diverges past whole blocks,
    and the alias is visible in events + counters."""
    from apex_tpu.obs import bridge as obs_bridge

    shared = _prompt(seed=15, n=64)
    p1 = shared + _prompt(seed=16, n=4)
    p2 = shared + _prompt(seed=17, n=4)
    _, paged = _engines(model, params, slots=1, block_size=16)
    sched = sv.ContinuousBatchingScheduler(
        paged, log_interval=10 ** 9,
        prefix_caching=sv.PrefixCacheConfig())
    alias0 = obs_bridge.SERVING_BLOCK_ALIAS_HITS.value()
    with _EventTap() as tap:
        for i, p in enumerate((p1, p2)):
            sched.submit(sv.Request(f"r{i}", p, max_new_tokens=4))
        sched.run()
    hits = tap.of("serving_prefix_hit")
    assert len(hits) == 1 and hits[0]["saved_tokens"] == 64
    alias = tap.of("serving_block_alias")
    assert len(alias) == 1 and alias[0]["blocks"] == 4
    # THE witness: zero restore compiles, zero region-read compiles —
    # the hit was table aliasing, not a copy through any program
    assert paged.restore_compiles() == 0
    assert compile_count(paged._read) == 0
    assert paged.block_stats()["aliased_total"] == 4
    assert obs_bridge.SERVING_BLOCK_ALIAS_HITS.value() == alias0 + 4
    assert obs_bridge.SERVING_BLOCK_POOL_UTILIZATION.value() \
        == paged.block_pool_utilization()
    # both streams produced tokens (sanity on the hit path)
    assert all(len(r.tokens) == 4 for r in sched.results.values())


def test_paged_prefix_store_by_reference_semantics(model, params):
    """put_block_ids is idempotent per chain position, refuses orphans,
    rejects span-mode calls, and clear() returns every cached block's
    reference to the pool."""
    _, paged = _engines(model, params, slots=1, block_size=16)
    mgr = paged.block_pool
    prompt = _prompt(seed=18, n=40)
    paged.prefill(0, prompt)
    ids = mgr.slot_block_ids(0)
    pc = sv.PrefixCache(block_size=16, max_tokens=1 << 20, pool=mgr,
                        bytes_per_block=128)
    blocks = [prompt[:16], prompt[16:32]]
    a, b = pc.put_block_ids(sv.PrefixCache.ROOT, blocks, ids[:2])
    assert [mgr.refcount(i) for i in ids[:2]] == [2, 2]
    assert pc.cached_bytes == 2 * 128
    again = pc.put_block_ids(sv.PrefixCache.ROOT, blocks, ids[:2])
    assert again == [a, b]                       # idempotent, no re-ref
    assert [mgr.refcount(i) for i in ids[:2]] == [2, 2]
    gone = sv.PrefixCache.chain_hash(sv.PrefixCache.ROOT, (0,) * 16)
    assert pc.put_block_ids(gone, [prompt[:16]], [ids[0]]) == []
    assert pc.stats()["refused"] == 1
    with pytest.raises(ValueError):              # span call on paged store
        pc.put_blocks(sv.PrefixCache.ROOT, [prompt[:16]],
                      jnp.zeros((2, 16, 2, 16)), jnp.zeros((2, 16, 2, 16)))
    with pytest.raises(ValueError):              # and the reverse
        sv.PrefixCache(block_size=16, max_tokens=4).put_block_ids(
            sv.PrefixCache.ROOT, [prompt[:16]], [1])
    with pytest.raises(ValueError):              # no materializing aliases
        sv.PrefixCache.gather_kv([a, b])
    pc.clear()
    assert [mgr.refcount(i) for i in ids[:2]] == [1, 1]


def test_paged_off_identity_and_guards(model, params):
    """A dense engine reports inert paged state, rejects paged-only
    calls loudly, and the paged engine rejects the dense capture
    family — no silent wrong-layout fallbacks."""
    dense, paged = _engines(model, params)
    assert dense.paged is None and dense.block_pool is None
    assert dense.block_size is None and dense.free_blocks() is None
    assert dense.block_pool_utilization() == 0.0
    assert dense.block_stats() == {}
    for call in (lambda: dense.slot_block_ids(0),
                 lambda: dense.alias_prefix(0, [1], 16),
                 lambda: dense.fork_slot(0, 1),
                 lambda: dense.set_block_reclaim(lambda n: 0)):
        with pytest.raises(ValueError):
            call()
    dense.prefill(0, _prompt(seed=19, n=8))
    paged.prefill(0, _prompt(seed=19, n=8))
    with pytest.raises(ValueError):              # capture is by reference
        paged.read_region(0, 0, 8)
    with pytest.raises(ValueError):              # hits alias, never copy
        paged.restore_prefix(1, (jnp.zeros((2, 8, 2, 16)),) * 2, 8)
    with pytest.raises(ValueError):              # mismatched prefix block
        sv.ContinuousBatchingScheduler(
            paged, prefix_caching=sv.PrefixCacheConfig(block_size=8))
    with pytest.raises(ValueError):              # block_size > max_len
        sv.DecodeEngine(model, params, slots=1, max_len=8, prefill_len=4,
                        paged=sv.PagedCacheConfig(block_size=16))
    # aliasing guards
    with pytest.raises(ValueError):              # occupied slot
        paged.alias_prefix(0, [1], 16)
    with pytest.raises(ValueError):              # id count != token need
        paged.alias_prefix(1, [1, 2], 16)
