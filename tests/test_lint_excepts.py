"""tools/check_excepts.py wired into tier-1: no NEW silent broad-except
swallowing lands without either a trace (log/raise/store) or a conscious
allowlist entry (ISSUE 2 satellite)."""

import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import check_excepts  # noqa: E402


def test_repo_has_no_silent_broad_excepts():
    violations = check_excepts.find_violations()
    assert violations == [], (
        "silent broad except handlers (log, narrow, or allowlist them): "
        f"{violations}")


def test_allowlist_has_no_stale_entries():
    """Every allowlist entry must still match a real broad-and-silent
    handler — the list can only shrink or be consciously re-justified."""
    assert check_excepts.stale_allowlist() == []


def _scan_source(tmp_path, source):
    path = tmp_path / "sample.py"
    path.write_text(textwrap.dedent(source))
    return check_excepts._scan_file(str(path))


def test_lint_flags_a_seeded_swallow(tmp_path):
    hits = _scan_source(tmp_path, """\
        def quiet():
            try:
                work()
            except Exception:
                pass
    """)
    assert [(lineno, qual) for _, lineno, qual in hits] == [(4, "quiet")]


def test_lint_flags_bare_except_and_tuple_forms(tmp_path):
    hits = _scan_source(tmp_path, """\
        class C:
            def a(self):
                try:
                    work()
                except:
                    x = 1
            def b(self):
                try:
                    work()
                except (ValueError, BaseException):
                    return None
    """)
    assert [qual for _, _, qual in hits] == ["C.a", "C.b"]


def test_lint_accepts_traced_handlers(tmp_path):
    """Logging, re-raising, narrowing, and store-forwarding all pass."""
    hits = _scan_source(tmp_path, """\
        def logged():
            try:
                work()
            except Exception as e:
                logger.debug("failed: %s", e)

        def reraised():
            try:
                work()
            except Exception as e:
                raise RuntimeError("wrapped") from e

        def narrowed():
            try:
                work()
            except ValueError:
                pass

        def forwarded(self):
            try:
                work()
            except BaseException as e:
                self._error = e
    """)
    assert hits == []
