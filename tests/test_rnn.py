"""apex_tpu.RNN vs torch.nn.LSTM/GRU/RNN CPU oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.RNN import GRU, LSTM, ReLU, Tanh, mLSTM


def _copy_params_to_torch(params, t_rnn, num_layers, bidirectional):
    """Load our params into a torch RNN (transposed: torch is [gate, in])."""
    import torch

    dirs = 2 if bidirectional else 1
    sd = {}
    for layer in range(num_layers):
        for d in range(dirs):
            ours = params["params"][f"layer{layer}_dir{d}"]
            sfx = "_reverse" if d == 1 else ""
            sd[f"weight_ih_l{layer}{sfx}"] = torch.from_numpy(
                np.asarray(ours["w_ih"]).T.copy())
            sd[f"weight_hh_l{layer}{sfx}"] = torch.from_numpy(
                np.asarray(ours["w_hh"]).T.copy())
            sd[f"bias_ih_l{layer}{sfx}"] = torch.from_numpy(
                np.asarray(ours["b_ih"]).copy())
            sd[f"bias_hh_l{layer}{sfx}"] = torch.from_numpy(
                np.asarray(ours["b_hh"]).copy())
    t_rnn.load_state_dict(sd)


@pytest.mark.parametrize("bidirectional", [False, True])
@pytest.mark.parametrize("kind", ["lstm", "gru", "relu", "tanh"])
def test_rnn_matches_torch(kind, bidirectional):
    import torch

    T, B, F, H, L = 5, 3, 4, 6, 2
    rng = np.random.default_rng(0)
    x = rng.standard_normal((T, B, F)).astype(np.float32)

    factory = {"lstm": LSTM, "gru": GRU, "relu": ReLU, "tanh": Tanh}[kind]
    model = factory(F, H, L, bias=True, bidirectional=bidirectional)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out, finals = model.apply(params, jnp.asarray(x))

    t_cls = {"lstm": torch.nn.LSTM, "gru": torch.nn.GRU,
             "relu": lambda *a, **k: torch.nn.RNN(*a, nonlinearity="relu", **k),
             "tanh": lambda *a, **k: torch.nn.RNN(*a, nonlinearity="tanh", **k),
             }[kind]
    t_rnn = t_cls(F, H, L, bidirectional=bidirectional)
    _copy_params_to_torch(params, t_rnn, L, bidirectional)
    with torch.no_grad():
        t_out, _ = t_rnn(torch.from_numpy(x))

    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_rnn_batch_first_and_hidden_roundtrip():
    T, B, F, H = 4, 2, 3, 5
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((B, T, F)), jnp.float32)
    model = LSTM(F, H, 1, batch_first=True)
    params = model.init(jax.random.PRNGKey(0), x)
    out, finals = model.apply(params, x)
    assert out.shape == (B, T, H)
    # final hidden feeds a continuation: running the same sequence in two
    # halves equals running it whole
    out_a, hid = model.apply(params, x[:, :2])
    out_b, _ = model.apply(params, x[:, 2:], hid)
    np.testing.assert_allclose(np.asarray(jnp.concatenate([out_a, out_b], 1)),
                               np.asarray(out), rtol=1e-5, atol=1e-6)


def test_mlstm_runs_and_differs_from_lstm():
    T, B, F, H = 4, 2, 3, 5
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((T, B, F)), jnp.float32)
    m = mLSTM(F, H, 1)
    params = m.init(jax.random.PRNGKey(0), x)
    out, _ = m.apply(params, x)
    assert out.shape == (T, B, H)
    assert "w_mih" in params["params"]["layer0_dir0"]
    g = jax.grad(lambda p: jnp.sum(m.apply(p, x)[0] ** 2))(params)
    assert all(np.all(np.isfinite(l)) for l in jax.tree.leaves(g))
