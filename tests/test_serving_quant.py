"""Quantized serving (ISSUE 19): int8 weights, int8 KV cache,
quantized tp collectives.

Acceptance is **agreement-tier**, not bit-tier: a quantized engine's
pinned greedy stream must agree with the fp32 engine's at a high rate
with bounded per-position logit error — quantization is a real
rounding step, so the fp bit-exactness ladder does not apply across
the fp/quant boundary.  *Within* a quantized engine every structural
guarantee still holds bit-for-bit and is pinned here: chunk splits are
invisible, paged ≡ dense, speculation ≡ plain decode, preemption
capture → restore ≡ uninterrupted — the same values/extents/op-order
argument as fp32, just over int8 bytes.  The default-off path
(``quant=None``) is byte-for-byte the fp engine: no quant events, no
quant cache types, no QTensor leaves, untouched quant metrics.

Plus: the one-spelling-site int8 primitives against a numpy oracle,
compile-count guards for every program family under quant (dequant
runs INSIDE the existing jitted bodies — no new program family), the
streams-per-GB capacity bar, quant-aware tp param specs, checkpoint
loading with ``quantize=True``, hot-swap requantization, and the
``serving_quant_eval`` → metrics bridge plumbing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _logging
from apex_tpu import serving as sv
from apex_tpu.amp.quant import INT8_QMAX, dequantize_int8, quantize_int8
from apex_tpu.models import LlamaConfig, LlamaForCausalLM
from apex_tpu.obs import bridge as obs_bridge
from apex_tpu.serving.engine import TPConfig, tp_param_shardings
from apex_tpu.serving.kv_cache import QuantKVCache
from apex_tpu.serving.paged_kv_cache import (PagedCacheConfig,
                                             QuantPagedKVCache,
                                             bytes_per_block)
from apex_tpu.serving.quant import (QTensor, QuantConfig, dequant_params,
                                    evaluate_quant, is_quantized,
                                    kv_bytes_per_token, max_logit_error,
                                    param_bytes, quantize_params,
                                    serving_param_spec, stream_agreement)

# GQA like test_serving_tp.py: kv_heads (2) < heads (4)
CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=256)
MAX = 96
W_KV = QuantConfig(weights=True, kv=True)


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM(CFG)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def _prompt(seed=0, n=12):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(1, CFG.vocab_size, n)]


def _greedy(eng, prompt, steps, slot=0):
    """Greedy stream + per-position decode logits off one slot."""
    logits = eng.prefill(slot, list(prompt))
    stream = [int(jnp.argmax(logits))]
    per_pos = []
    toks = np.zeros((eng.slots,), np.int32)
    act = np.zeros((eng.slots,), bool)
    act[slot] = True
    for _ in range(steps):
        toks[slot] = stream[-1]
        lg = np.asarray(eng.decode(toks, act)[slot])
        per_pos.append(lg)
        stream.append(int(lg.argmax()))
    return stream, np.stack(per_pos)


def _teacher_forced(eng, prompt, ref_stream, slot=0):
    """Per-position greedy picks with the REFERENCE stream fed in.

    Free-running streams cascade: one flipped argmax changes every
    subsequent input, so positionwise agreement measures divergence
    length, not quantization quality.  Teacher-forcing pins the inputs
    to the fp32 stream so each position is an independent same-prefix
    comparison — the honest per-token agreement rate."""
    logits = eng.prefill(slot, list(prompt))
    picks = [int(jnp.argmax(logits))]
    per_pos = []
    toks = np.zeros((eng.slots,), np.int32)
    act = np.zeros((eng.slots,), bool)
    act[slot] = True
    for tok in ref_stream[:-1]:
        toks[slot] = tok
        lg = np.asarray(eng.decode(toks, act)[slot])
        per_pos.append(lg)
        picks.append(int(lg.argmax()))
    return picks, np.stack(per_pos)


class _EventTap:
    def __init__(self):
        self.events = []

    def __enter__(self):
        self._sink = lambda e: self.events.append(dict(e))
        _logging.add_event_sink(self._sink)
        return self

    def __exit__(self, *exc):
        _logging.remove_event_sink(self._sink)

    def of(self, kind):
        return [e for e in self.events if e.get("event") == kind]


# ---------------------------------------------------------------------------
# the int8 primitives (one spelling site) vs a numpy oracle
# ---------------------------------------------------------------------------


class TestInt8Primitives:
    def test_matches_numpy_oracle_last_axis(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        q, scale = quantize_int8(jnp.asarray(x), axis=-1)
        amax = np.abs(x).max(axis=-1)
        want_scale = amax / 127.0
        np.testing.assert_allclose(np.asarray(scale), want_scale,
                                   rtol=1e-6)
        want_q = np.clip(np.round(x / want_scale[:, None]), -127, 127)
        assert np.asarray(q).dtype == np.int8
        np.testing.assert_array_equal(np.asarray(q),
                                      want_q.astype(np.int8))

    def test_axis0_scale_per_output_channel(self):
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(6, 10)).astype(np.float32))
        q, scale = quantize_int8(x, axis=0)
        assert q.shape == (6, 10) and scale.shape == (10,)
        dq = dequantize_int8(q, scale, axis=0)
        assert dq.shape == x.shape and dq.dtype == jnp.float32

    def test_zero_group_takes_scale_one(self):
        """An all-zero group must take scale 1.0 (not 0): unallocated
        quant-cache rows dequantize to exact finite zeros — masked
        attention reads must never meet 0 * inf = NaN."""
        x = jnp.zeros((4, 8), jnp.float32)
        q, scale = quantize_int8(x, axis=-1)
        np.testing.assert_array_equal(np.asarray(scale),
                                      np.ones((4,), np.float32))
        np.testing.assert_array_equal(
            np.asarray(dequantize_int8(q, scale, axis=-1)),
            np.zeros((4, 8), np.float32))

    def test_roundtrip_error_bounded_by_half_scale(self):
        rng = np.random.default_rng(2)
        x = (rng.normal(size=(16, 32)) * 10).astype(np.float32)
        q, scale = quantize_int8(jnp.asarray(x), axis=-1)
        dq = np.asarray(dequantize_int8(q, scale, axis=-1))
        bound = np.asarray(scale)[:, None] * 0.5 * (1 + 1e-5)
        assert np.all(np.abs(x - dq) <= bound)

    def test_amax_element_requantizes_exactly(self):
        """The group amax element maps to exactly ±127, so a payload
        survives dequantize → requantize bit-for-bit — the property
        that makes KV capture → restore reproduce stored int8 bytes."""
        assert INT8_QMAX == 127.0
        rng = np.random.default_rng(3)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        q1, s1 = quantize_int8(jnp.asarray(x), axis=-1)
        dq = dequantize_int8(q1, s1, axis=-1)
        q2, s2 = quantize_int8(dq, axis=-1)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-6)


# ---------------------------------------------------------------------------
# weight quantization: exactly the projections + lm_head, idempotent
# ---------------------------------------------------------------------------


class TestWeightQuant:
    def test_targets_exactly_projections_and_lm_head(self, params):
        qp = quantize_params(params)
        assert is_quantized(qp) and not is_quantized(params)
        flat = {jax.tree_util.keystr(p): l
                for p, l in jax.tree_util.tree_flatten_with_path(
                    qp, is_leaf=lambda x: isinstance(x, QTensor))[0]}
        quantized = {k for k, v in flat.items()
                     if isinstance(v, QTensor)}
        for mod in ("q_proj", "k_proj", "v_proj", "o_proj",
                    "gate_proj", "up_proj", "down_proj", "lm_head"):
            assert any(mod in k for k in quantized), mod
        # embedding and norm scales stay high-precision
        for k, v in flat.items():
            if "embed" in k or "norm" in k.lower():
                assert not isinstance(v, QTensor), k
        # per-output-channel layout: [in, out] kernels reduce axis 0,
        # the [vocab, h] lm_head reduces axis 1
        for k, v in flat.items():
            if not isinstance(v, QTensor):
                continue
            assert v.q.dtype == jnp.int8 and v.scale.dtype == jnp.float32
            if "lm_head" in k:
                assert v.axis == 1 and v.scale.shape == (v.q.shape[0],)
            else:
                assert v.axis == 0 and v.scale.shape == (v.q.shape[1],)

    def test_idempotent_and_dequant_bounded(self, params):
        qp = quantize_params(params)
        again = quantize_params(qp)
        assert jax.tree.all(jax.tree.map(
            lambda a, b: a is b or bool(jnp.array_equal(a, b)),
            qp, again))
        # dequant restores shape/dtype with per-channel-bounded error
        dq = dequant_params(qp)
        for p, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            ks = jax.tree_util.keystr(p)
            got = dq
            for part in p:
                got = got[part.key if hasattr(part, "key") else
                          part.name if hasattr(part, "name") else part]
            assert got.shape == leaf.shape and got.dtype == leaf.dtype, ks
        assert param_bytes(qp) < param_bytes(params)


# ---------------------------------------------------------------------------
# default-off identity: quant=None IS the fp engine
# ---------------------------------------------------------------------------


def test_default_off_is_byte_identical_fp_engine(model, params):
    agree0 = obs_bridge.SERVING_QUANT_AGREEMENT.value()
    err0 = obs_bridge.SERVING_QUANT_LOGIT_ERROR.count()
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16)
    assert eng.quant is None
    assert not is_quantized(eng.params)
    assert not isinstance(eng.cache, (QuantKVCache, QuantPagedKVCache))
    with _EventTap() as tap:
        _greedy(eng, _prompt(), steps=6)
    assert tap.of("serving_quant_enabled") == []
    assert tap.of("serving_quant_eval") == []
    assert obs_bridge.SERVING_QUANT_AGREEMENT.value() == agree0
    assert obs_bridge.SERVING_QUANT_LOGIT_ERROR.count() == err0


def test_config_validation(model, params):
    with pytest.raises(ValueError, match="every lever off"):
        QuantConfig(weights=False, kv=False, allreduce=False)
    with pytest.raises(ValueError, match="tp"):
        sv.DecodeEngine(model, params, slots=1, max_len=32,
                        prefill_len=8,
                        quant=QuantConfig(allreduce=True))
    with pytest.raises(ValueError, match="cache_dtype"):
        sv.DecodeEngine(model, params, slots=1, max_len=32,
                        prefill_len=8, cache_dtype=jnp.bfloat16,
                        quant=QuantConfig(weights=False, kv=True))


# ---------------------------------------------------------------------------
# THE acceptance run: agreement-tier greedy streams, bounded drift,
# unchanged compile discipline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fp_ref(model, params):
    """One warm fp32 reference engine + its pinned greedy stream,
    shared by every agreement-tier comparison (a fresh DecodeEngine
    recompiles its whole program family — don't pay that per test)."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16)
    s_ref, l_ref = _greedy(eng, _prompt(), steps=24)
    eng.reset()
    return eng, s_ref, l_ref


@pytest.mark.parametrize("quant", [
    QuantConfig(weights=True, kv=False),
    QuantConfig(weights=False, kv=True),
    W_KV,
], ids=["weights", "kv", "weights+kv"])
def test_quant_greedy_agreement_and_compiles(model, params, fp_ref,
                                             quant):
    ref, s_ref, l_ref = fp_ref
    with _EventTap() as tap:
        eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                              prefill_len=16, quant=quant)
    (enabled,) = tap.of("serving_quant_enabled")
    assert enabled["weights"] == quant.weights
    assert enabled["kv"] == quant.kv
    assert eng.quant == quant
    assert is_quantized(eng.params) == quant.weights
    assert isinstance(eng.cache, QuantKVCache) == quant.kv
    s_q, l_q = _teacher_forced(eng, _prompt(), s_ref)
    # the acceptance bars: high greedy agreement, bounded logit drift
    assert stream_agreement(s_ref, s_q) >= 0.9
    assert max_logit_error(l_ref, l_q) < 0.5
    # dequant rides INSIDE the existing program families
    assert eng.decode_compiles() == 1
    assert eng.prefill_compiles() == ref.prefill_compiles()


def test_kv_int8_capacity_bar(model, params, fp_ref):
    """The streams-per-GB claim: fp bytes / quant bytes per cached
    token >= 1.8x (payload 2·hd·4 vs 2·hd + 2·4 per (pos, head))."""
    fp = fp_ref[0]
    q = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                        prefill_len=16,
                        quant=QuantConfig(weights=False, kv=True))
    assert q.cache.k.dtype == jnp.int8
    assert q.cache.k_scale.dtype == jnp.float32
    ratio = kv_bytes_per_token(fp.cache) / kv_bytes_per_token(q.cache)
    assert ratio >= 1.8
    # hd=16 here: exact ratio is (2*16*4) / (2*16 + 2*4) = 3.2
    assert ratio == pytest.approx(3.2)


# ---------------------------------------------------------------------------
# within-quant structural bit-exactness: chunk splits, preemption,
# prefix caching, speculation, paged/CoW
# ---------------------------------------------------------------------------


def test_chunked_prefill_invisible_under_quant(model, params):
    """Chunk boundaries are scheduling, not numerics, under KV-int8
    too: per-(position, head) scales depend only on the row being
    written, never on which chunk wrote it."""
    prompt = _prompt(seed=3, n=40)
    small = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                            prefill_len=16, quant=W_KV)
    big = sv.DecodeEngine(model, params, slots=1, max_len=MAX,
                          prefill_len=64, quant=W_KV)
    s_small, l_small = _greedy(small, prompt, steps=8)
    s_big, l_big = _greedy(big, prompt, steps=8)
    assert s_small == s_big
    np.testing.assert_array_equal(l_small, l_big)


def test_preempt_capture_restore_bit_exact_under_quant(model, params):
    """Lossless preemption composes with KV-int8: capture hands out
    dequantized fp32 rows, restore requantizes in-program, and because
    the group amax requantizes to exactly ±127 the stored int8 payload
    reproduces bit-for-bit.  The regrouped *scale* can move by one ulp
    (``amax/127 * 127 / 127`` is not an fp32 identity), so resumed
    logits carry ~1e-7 float noise — the greedy stream must still be
    identical, and the logits equal to fp tolerance."""
    prompt = _prompt(seed=4)
    ref = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV)
    s_ref, l_ref = _greedy(ref, prompt, steps=12)

    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV)
    s_pre, _ = _greedy(eng, prompt, steps=6)
    assert s_pre == s_ref[:7]
    k, v, length = eng.capture_slot(0)
    # capture is quantization-oblivious: fp32 host bytes
    assert k.dtype == np.float32 and v.dtype == np.float32
    assert length == len(prompt) + 6
    eng.release(0)
    eng.restore_prefix(1, (k, v), length)
    toks = np.zeros((2,), np.int32)
    act = np.array([False, True])
    stream = list(s_pre)
    per_pos = []
    for _ in range(6):
        toks[1] = stream[-1]
        lg = np.asarray(eng.decode(toks, act)[1])
        per_pos.append(lg)
        stream.append(int(lg.argmax()))
    assert stream == s_ref
    np.testing.assert_allclose(np.stack(per_pos), l_ref[6:],
                               rtol=1e-5, atol=1e-5)


def test_prefix_cache_hit_bit_exact_under_quant(model, params):
    """A prefix-cache hit on a KV-int8 engine restores the dequantized
    span and requantizes to the same stored bytes: warm admission's
    stream is bit-identical to the cold one."""
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV)
    sched = sv.ContinuousBatchingScheduler(
        eng, log_interval=10 ** 9,
        prefix_caching=sv.PrefixCacheConfig())
    prompt = _prompt(seed=5, n=34)
    with _EventTap() as tap:
        sched.submit(sv.Request("cold", prompt, max_new_tokens=6))
        sched.run()
        sched.submit(sv.Request("warm", prompt, max_new_tokens=6))
        sched.run()
    assert len(tap.of("serving_prefix_hit")) == 1
    assert (sched.results["warm"].tokens
            == sched.results["cold"].tokens)
    sched.close()


def test_speculation_exact_under_quant(model, params):
    """verify_draft on a quantized engine is still an exact test
    against the engine's OWN plain-decode stream: a correct draft is
    fully accepted, a wrong token rejected at its position, and the
    emitted tokens match plain decode bit-for-bit."""
    prompt = _prompt(seed=6)
    plain = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                            prefill_len=16, quant=W_KV)
    s_plain, _ = _greedy(plain, prompt, steps=6)

    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV)
    logits = eng.prefill(0, prompt)
    pending = int(jnp.argmax(logits))
    assert pending == s_plain[0]
    # correct draft: the plain continuation — fully accepted
    draft = s_plain[1:4]
    accepted, greedy, _ = eng.verify_draft(0, [pending] + draft)
    assert accepted == len(draft)
    emitted = draft[:accepted] + [int(greedy[accepted])]
    assert emitted == s_plain[1:5]
    # wrong continuation: rejected at its position, bonus row still
    # equals the plain stream's token there
    bad = [s_plain[5], (s_plain[6] + 1) % CFG.vocab_size]
    accepted2, greedy2, _ = eng.verify_draft(
        0, [s_plain[4]] + bad)
    assert accepted2 == 1
    assert int(greedy2[accepted2]) == s_plain[6]
    assert eng.verify_compiles() >= 1
    assert eng.decode_compiles() == 0


def test_paged_quant_identical_to_dense_quant(model, params):
    """Same writes routed through the block pool: the paged KV-int8
    stream is bit-identical to the dense KV-int8 stream (pool + scale
    pool gathers reproduce the dense rows exactly)."""
    dense = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                            prefill_len=16, quant=W_KV)
    paged = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                            prefill_len=16, quant=W_KV,
                            paged=PagedCacheConfig(block_size=8))
    assert isinstance(paged.cache, QuantPagedKVCache)
    s_dense, l_dense = _greedy(dense, _prompt(seed=7), steps=10)
    s_paged, l_paged = _greedy(paged, _prompt(seed=7), steps=10)
    assert s_paged == s_dense
    np.testing.assert_array_equal(l_paged, l_dense)
    # scale pools ride the same block accounting: per-block bytes
    # count payload + scales (the scheduler's admission pricing)
    assert bytes_per_block(paged.cache) > bytes_per_block(
        dense_like_block(paged.cache))


def dense_like_block(cache):
    """A payload-only view for the bytes_per_block comparison: the
    quant pool must price strictly MORE than its payload alone."""
    import dataclasses as _dc

    class _Payload:
        pass

    p = _Payload()
    p.k, p.v = cache.k, cache.v
    return p


def test_paged_cow_fork_isolated_under_quant(model, params):
    """fork_slot + divergent decode under KV-int8: copy-on-write moves
    payload AND scales together (same block ids index both pools), so
    the parent stream is bit-unchanged by the child's writes."""
    prompt = _prompt(seed=8)
    ref = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV,
                          paged=PagedCacheConfig(block_size=8))
    s_ref, l_ref = _greedy(ref, prompt, steps=8)

    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV,
                          paged=PagedCacheConfig(block_size=8))
    s_pre, _ = _greedy(eng, prompt, steps=4)
    eng.fork_slot(0, 1)
    toks = np.zeros((2,), np.int32)
    act = np.array([True, True])
    stream = list(s_pre)
    per_pos = []
    for i in range(4):
        toks[0] = stream[-1]
        # the fork decodes a DIFFERENT token every step — its CoW
        # copies must never leak into the parent's blocks
        toks[1] = (stream[-1] + 1 + i) % CFG.vocab_size
        lg = np.asarray(eng.decode(toks, act))
        per_pos.append(lg[0])
        stream.append(int(lg[0].argmax()))
    assert stream == s_ref
    np.testing.assert_array_equal(np.stack(per_pos), l_ref[4:])


# ---------------------------------------------------------------------------
# tensor parallel: quant-aware shardings + quantized allreduce
# ---------------------------------------------------------------------------


def test_tp2_quant_stream_matches_single_chip(model, params):
    single = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                             prefill_len=16, quant=W_KV)
    tp2 = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV,
                          tp=TPConfig(size=2))
    s1, l1 = _greedy(single, _prompt(), steps=12)
    s2, l2 = _greedy(tp2, _prompt(), steps=12)
    assert s1 == s2
    np.testing.assert_allclose(l2, l1, rtol=1e-4, atol=1e-4)
    assert tp2.decode_compiles() == 1


def test_tp2_quantized_allreduce_agreement(model, params, fp_ref):
    """The int8 psum pair is the one knowingly lossy-per-step leg:
    agreement-tier against the exact-collective fp32 engine, same
    compile discipline, scoped to the row-linear reduces only."""
    ref, s_ref, l_ref = fp_ref
    eng = sv.DecodeEngine(
        model, params, slots=2, max_len=MAX, prefill_len=16,
        tp=TPConfig(size=2),
        quant=QuantConfig(weights=False, kv=False, allreduce=True))
    s_q, l_q = _teacher_forced(eng, _prompt(), s_ref)
    assert stream_agreement(s_ref, s_q) >= 0.8
    assert max_logit_error(l_ref, l_q) < 1.0
    assert eng.decode_compiles() == 1
    assert eng.prefill_compiles() == ref.prefill_compiles()


def test_quant_param_specs_follow_replaced_kernels(params):
    """A QTensor's .q shards exactly like the kernel it replaced; its
    per-output-channel .scale shards with the OUTPUT dim — split for
    column kernels + lm_head, replicated for row kernels; non-QTensor
    leaves (norm ['scale'] dict keys included) delegate untouched."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.llama import tp_param_spec

    qp = quantize_params(params)
    leaves = jax.tree_util.tree_flatten_with_path(qp)[0]
    seen = {"col_scale": 0, "row_scale": 0, "plain": 0}
    for path, _ in leaves:
        ks = jax.tree_util.keystr(path)
        spec = serving_param_spec(ks, "tp")
        if ks.endswith(".q"):
            assert spec == tp_param_spec(ks[:-2], "tp"), ks
        elif ks.endswith(".scale"):
            if "o_proj" in ks or "down_proj" in ks:
                assert spec == P(), ks
                seen["row_scale"] += 1
            else:
                assert spec == P("tp"), ks
                seen["col_scale"] += 1
        else:
            assert spec == tp_param_spec(ks, "tp"), ks
            seen["plain"] += 1
    assert all(seen.values())


def test_pre_quantized_params_accepted_by_tp_engine(model, params):
    """quantize_params ahead of construction (the load-time path):
    the engine detects the QTensor tree, skips its own requantization,
    and tp_param_shardings lays the quant leaves out mesh-correctly."""
    qp = quantize_params(params)
    eng = sv.DecodeEngine(model, qp, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV,
                          tp=TPConfig(size=2))
    shardings = tp_param_shardings(qp, eng.mesh)
    assert (jax.tree.structure(shardings, is_leaf=lambda x: x is None)
            == jax.tree.structure(qp))
    ref = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV)
    s_ref, _ = _greedy(ref, _prompt(seed=9), steps=8)
    s_tp, _ = _greedy(eng, _prompt(seed=9), steps=8)
    assert s_tp == s_ref


# ---------------------------------------------------------------------------
# load-time quantization + hot-swap requantization
# ---------------------------------------------------------------------------


def test_load_serving_params_quantize(tmp_path, model, params):
    from apex_tpu.resilience.checkpoint import save_checkpoint

    root = str(tmp_path / "ckpt")
    save_checkpoint(root, 3, {"params": params})
    loaded, step = sv.load_serving_params(
        root, {"params": params}, params_key="params", quantize=True)
    assert step == 3 and is_quantized(loaded)
    eng = sv.DecodeEngine(model, loaded, slots=2, max_len=MAX,
                          prefill_len=16,
                          quant=QuantConfig(weights=True, kv=False))
    ref = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16,
                          quant=QuantConfig(weights=True, kv=False))
    s_loaded, _ = _greedy(eng, _prompt(), steps=8)
    s_boot, _ = _greedy(ref, _prompt(), steps=8)
    # load-time and boot-time quantization are the same function on
    # the same bytes: identical streams
    assert s_loaded == s_boot


def test_swap_params_requantizes(model, params):
    eng = sv.DecodeEngine(model, params, slots=2, max_len=MAX,
                          prefill_len=16, quant=W_KV)
    s_before, _ = _greedy(eng, _prompt(), steps=6)
    eng.reset()
    eng.swap_params(params)          # fp candidate: requantized on swap
    assert is_quantized(eng.params)
    s_after, _ = _greedy(eng, _prompt(), steps=6)
    assert s_after == s_before
    assert eng.decode_compiles() == 1


# ---------------------------------------------------------------------------
# acceptance accounting + the metrics bridge
# ---------------------------------------------------------------------------


def test_evaluate_quant_feeds_bridge_metrics():
    err0 = obs_bridge.SERVING_QUANT_LOGIT_ERROR.count()
    with _EventTap() as tap:
        report = evaluate_quant(
            [1, 2, 3, 4], [1, 2, 9, 4],
            ref_logits=np.zeros((2, 4), np.float32),
            quant_logits=np.full((2, 4), 0.25, np.float32),
            bytes_per_token=160.0, fp_bytes_per_token=512.0)
    assert report["agreement"] == pytest.approx(0.75)
    assert report["tokens"] == 4
    assert report["max_logit_error"] == pytest.approx(0.25)
    assert report["capacity_ratio"] == pytest.approx(3.2)
    (ev,) = tap.of("serving_quant_eval")
    assert ev["agreement"] == pytest.approx(0.75)
    assert obs_bridge.SERVING_QUANT_AGREEMENT.value() == pytest.approx(
        0.75)
    assert obs_bridge.SERVING_QUANT_BYTES_PER_TOKEN.value() == 160.0
    assert obs_bridge.SERVING_QUANT_LOGIT_ERROR.count() == err0 + 1


def test_stream_helpers():
    assert stream_agreement([], []) == 1.0
    assert stream_agreement([1, 2], [1, 2, 3]) == 1.0
    assert stream_agreement([1, 2, 3], [1, 0, 3]) == pytest.approx(2 / 3)
    assert max_logit_error(np.zeros((0, 4)), np.zeros((0, 4))) == 0.0
