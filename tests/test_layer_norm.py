"""Parity tests for fused LayerNorm/RMSNorm (mirrors tests/L0/run_fused_layer_norm).

The reference compares its CUDA kernels against torch.nn.functional references
across dtypes/shapes/memory_efficient; we compare the fused path (jnp fallback
and, via APEX_TPU_KERNELS=interpret, the Pallas kernels) against plain jnp.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)


def _ref_ln(x, w=None, b=None, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def _ref_rms(x, w=None, eps=1e-5):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 / jnp.sqrt(ms + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    return y.astype(x.dtype)


@pytest.mark.parametrize("mem_eff", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layer_norm_affine_forward(rng, dtype, mem_eff):
    x = jnp.asarray(rng.standard_normal((4, 16, 64)), dtype)
    w = jnp.asarray(1.0 + 0.1 * rng.standard_normal(64), jnp.float32)
    b = jnp.asarray(0.1 * rng.standard_normal(64), jnp.float32)
    y = fused_layer_norm_affine(x, w, b, (64,), memory_efficient=mem_eff)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(_ref_ln(x, w, b), np.float32),
                               rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5,
                               atol=2e-2 if dtype == jnp.bfloat16 else 1e-5)


@pytest.mark.parametrize("mem_eff", [False, True])
def test_layer_norm_affine_grads(rng, mem_eff):
    x = jnp.asarray(rng.standard_normal((8, 32)), jnp.float32)
    w = jnp.asarray(1.0 + 0.1 * rng.standard_normal(32), jnp.float32)
    b = jnp.asarray(0.1 * rng.standard_normal(32), jnp.float32)

    def fused_loss(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b, (32,),
                                                       memory_efficient=mem_eff)))

    def ref_loss(x, w, b):
        return jnp.sum(jnp.sin(_ref_ln(x, w, b)))

    g_f = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
    g_r = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)


def test_layer_norm_no_affine(rng):
    x = jnp.asarray(rng.standard_normal((3, 5, 32)), jnp.float32)
    y = fused_layer_norm(x, (32,))
    np.testing.assert_allclose(np.asarray(y), np.asarray(_ref_ln(x)), rtol=1e-5, atol=1e-5)
    # multi-dim normalized_shape normalizes over all trailing dims
    y2 = fused_layer_norm(x, (5, 32))
    ref2 = _ref_ln(x.reshape(3, -1)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(ref2), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mem_eff", [False, True])
def test_rms_norm_affine(rng, mem_eff):
    x = jnp.asarray(rng.standard_normal((4, 16, 64)), jnp.float32)
    w = jnp.asarray(1.0 + 0.1 * rng.standard_normal(64), jnp.float32)
    y = fused_rms_norm_affine(x, w, (64,), memory_efficient=mem_eff)
    np.testing.assert_allclose(np.asarray(y), np.asarray(_ref_rms(x, w)), rtol=1e-5, atol=1e-5)

    g_f = jax.grad(lambda x, w: jnp.sum(jnp.cos(
        fused_rms_norm_affine(x, w, (64,), memory_efficient=mem_eff))), argnums=(0, 1))(x, w)
    g_r = jax.grad(lambda x, w: jnp.sum(jnp.cos(_ref_rms(x, w))), argnums=(0, 1))(x, w)
    for a, e in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)


def test_rms_norm_no_affine(rng):
    x = jnp.asarray(rng.standard_normal((6, 128)), jnp.float32)
    y = fused_rms_norm(x, (128,))
    np.testing.assert_allclose(np.asarray(y), np.asarray(_ref_rms(x)), rtol=1e-5, atol=1e-5)


def test_pallas_interpret_parity(rng, monkeypatch):
    """Run the actual Pallas kernels in interpret mode and compare (lane-aligned H)."""
    monkeypatch.setenv("APEX_TPU_KERNELS", "interpret")
    x = jnp.asarray(rng.standard_normal((16, 128)), jnp.float32)
    w = jnp.asarray(1.0 + 0.1 * rng.standard_normal(128), jnp.float32)
    b = jnp.asarray(0.1 * rng.standard_normal(128), jnp.float32)
    y = fused_layer_norm_affine(x, w, b, (128,))
    np.testing.assert_allclose(np.asarray(y), np.asarray(_ref_ln(x, w, b)),
                               rtol=1e-5, atol=1e-5)
    g_f = jax.grad(lambda x, w, b: jnp.sum(
        jnp.sin(fused_layer_norm_affine(x, w, b, (128,)))), argnums=(0, 1, 2))(x, w, b)
    g_r = jax.grad(lambda x, w, b: jnp.sum(jnp.sin(_ref_ln(x, w, b))),
                   argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4)


def test_modules(rng):
    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    x = jnp.asarray(rng.standard_normal((2, 8, 32)), jnp.float32)
    ln = FusedLayerNorm(32)
    params = ln.init(jax.random.PRNGKey(0), x)
    y = ln.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(_ref_ln(x, jnp.ones(32), jnp.zeros(32))), rtol=1e-5, atol=1e-5)

    rn = FusedRMSNorm(32, elementwise_affine=False)
    y2 = rn.apply({}, x)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(_ref_rms(x)), rtol=1e-5, atol=1e-5)
