"""tools/check_metrics.py wired into tier-1: every metric under
``apex_tpu/`` keeps the naming conventions, is registered at exactly one
call site, and is documented in docs/api/observability.md (ISSUE 6
satellite)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import check_metrics  # noqa: E402


def test_repo_metrics_are_clean():
    problems = check_metrics.find_violations()
    assert problems == [], (
        "metric lint violations (fix the name, de-duplicate the "
        "registration, or document it): " + "\n".join(problems))


def test_every_registered_runtime_metric_is_collected_by_the_lint():
    """The static scan must see at least every metric the default
    registry actually holds after the instrumented subsystems import —
    a registration path the lint can't see would be unlintable."""
    import apex_tpu.resilience.supervisor  # noqa: F401 — registers metrics
    import apex_tpu.serving.scheduler  # noqa: F401
    from apex_tpu.obs import REGISTRY

    static_names = {r.name for r in check_metrics.collect()}
    for name in REGISTRY.names():
        assert name in static_names, (
            f"runtime metric {name!r} not found by the static scan")


# ---- seeded-violation unit tests (the lint must actually bite) ----------

def _check_src(source: str, doc: str | None = "") -> list:
    regs = check_metrics.collect_from_source(source, "sample.py")
    return check_metrics.check(regs, doc)


def test_lint_flags_bad_names():
    problems = _check_src(
        'c = metrics.counter("step_total", "no apex_ prefix")\n'
        'g = metrics.gauge("apex_BadCase", "uppercase")\n')
    assert len(problems) == 2
    assert "does not match" in problems[0]
    assert "does not match" in problems[1]


def test_lint_flags_missing_suffixes():
    problems = _check_src(
        'c = metrics.counter("apex_things", "counter sans _total")\n'
        'h = metrics.histogram("apex_latency", "histogram sans unit")\n')
    assert any("_total" in p for p in problems)
    assert any("unit" in p for p in problems)


def test_lint_flags_duplicate_registration():
    problems = _check_src(
        'a = metrics.counter("apex_dups_total", "one")\n'
        'b = reg.counter("apex_dups_total", "two")\n')
    assert any("2 call sites" in p for p in problems)


def test_lint_documentation_match_is_word_bounded():
    """A name that is a prefix of a documented name is still
    undocumented — substring containment must not pass it."""
    problems = _check_src(
        'c = metrics.gauge("apex_serving_tokens", "prefix of a real one")\n',
        doc="inventory: apex_serving_tokens_per_second")
    assert any("not documented" in p for p in problems)


def test_lint_flags_undocumented_and_missing_doc():
    problems = _check_src(
        'c = metrics.counter("apex_ghost_total", "undocumented")\n',
        doc="some page that never mentions it")
    assert any("not documented" in p for p in problems)
    problems = _check_src(
        'c = metrics.counter("apex_ghost_total", "undocumented")\n',
        doc=None)
    assert any("missing" in p for p in problems)


def test_lint_accepts_clean_registration():
    assert _check_src(
        'c = metrics.counter("apex_good_total", "fine")\n'
        'h = metrics.histogram("apex_lat_seconds", "fine")\n'
        'g = metrics.gauge("apex_depth", "fine")\n',
        doc="apex_good_total apex_lat_seconds apex_depth") == []


def test_lint_accepts_token_count_histograms():
    """``_tokens`` is a real unit on the serving path (the speculative
    acceptance-length histogram) — the lint accepts it alongside
    ``_seconds``/``_bytes`` without loosening the no-unit rejection."""
    assert _check_src(
        'h = metrics.histogram("apex_accept_tokens", "token counts")\n',
        doc="apex_accept_tokens") == []
    problems = _check_src(
        'h = metrics.histogram("apex_accept_count", "no unit")\n',
        doc="apex_accept_count")
    assert any("unit" in p for p in problems)


_CONVENTIONS = (
    "\n## Label cardinality\n\n"
    "| Label | Bound |\n|---|---|\n"
    "| `op` | fixed vocabulary |\n")


def test_lint_accepts_labeled_metric_with_matching_row():
    assert _check_src(
        'c = metrics.counter("apex_ops_total", "labeled", ("op",))\n',
        doc="| `apex_ops_total{op}` | counter | per-op |\n"
            + _CONVENTIONS) == []


def test_lint_collects_scope_labels():
    """``scope_labels=`` joins ``labelnames`` in the registration's
    label vocabulary — a doc row must spell both."""
    regs = check_metrics.collect_from_source(
        'h = metrics.histogram("apex_lat_seconds", "x", ("op",),\n'
        '                      scope_labels=("replica",))\n', "sample.py")
    assert regs[0].labels == ("op", "replica")
    problems = _check_src(
        'h = metrics.histogram("apex_lat_seconds", "x", ("op",),\n'
        '                      scope_labels=("replica",))\n',
        doc="| `apex_lat_seconds{op}` | histogram | x |\n" + _CONVENTIONS)
    assert any("['op', 'replica']" in p for p in problems)


def test_lint_flags_label_mismatch_both_ways():
    # registration labeled, doc row bare
    problems = _check_src(
        'c = metrics.counter("apex_ops_total", "labeled", ("op",))\n',
        doc="| `apex_ops_total` | counter | per-op |\n" + _CONVENTIONS)
    assert any("spell the label names" in p for p in problems)
    # doc row labeled, registration bare
    problems = _check_src(
        'c = metrics.counter("apex_ops_total", "bare")\n',
        doc="| `apex_ops_total{op}` | counter | per-op |\n" + _CONVENTIONS)
    assert any("spell the label names" in p for p in problems)


def test_lint_flags_undocumented_and_stale_convention_labels():
    # in-use label with no conventions row
    problems = _check_src(
        'c = metrics.counter("apex_ops_total", "labeled", ("op",))\n',
        doc="| `apex_ops_total{op}` | counter | per-op |\n")
    assert any("cardinality" in p and "'op'" in p for p in problems)
    # conventions row for a label nothing uses
    problems = _check_src(
        'c = metrics.counter("apex_plain_total", "bare")\n',
        doc="apex_plain_total\n" + _CONVENTIONS)
    assert any("stale row" in p for p in problems)


def test_lint_reserves_le():
    """``le`` belongs to histogram exposition: never declarable, never
    documented as a conventions row, and ignored in doc-row suffixes."""
    problems = _check_src(
        'c = metrics.counter("apex_plain_total", "bare")\n',
        doc="apex_plain_total\n"
            "\n## Label cardinality\n\n| `le` | bucket edges |\n")
    assert any("reserved" in p for p in problems)


def test_lint_ignores_non_literal_and_unrelated_calls():
    regs = check_metrics.collect_from_source(
        'x = registry.counter(name_var, "dynamic: out of scope")\n'
        'y = collections.Counter([1, 2])\n'
        'z = obj.histogram()\n', "sample.py")
    assert regs == []
