"""contrib.multihead_attn + fmha tests (mirrors
apex/contrib/test/multihead_attn/ and test/fmha numeric-parity style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.fmha import FMHA, fmha_varlen, segment_ids_from_cu_seqlens
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
    fast_mask_softmax_dropout_func,
)
from apex_tpu.ops.flash_attention import mha_reference


def _ref_attention(x, params, heads, key_padding_mask=None):
    """Dense [s,b,e] self-attention computed the long way for parity."""
    e = x.shape[-1]
    w = params["in_proj_weight"]
    y = x @ w.T
    q, k, v = jnp.split(y, 3, axis=-1)
    s, b = x.shape[0], x.shape[1]
    hd = e // heads

    def to_bhsd(t):
        return t.reshape(s, b, heads, hd).transpose(1, 2, 0, 3)

    seg = None
    if key_padding_mask is not None:
        kseg = jnp.where(key_padding_mask.astype(bool), 0, 1).astype(jnp.int32)
        qseg = jnp.ones((b, s), jnp.int32)
        seg = (qseg, kseg)
    ctx = mha_reference(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                        q_segment_ids=seg[0] if seg else None,
                        kv_segment_ids=seg[1] if seg else None)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, e)
    return ctx @ params["out_proj_weight"].T


def test_self_attn_fast_matches_default(rng):
    s, b, e, h = 16, 2, 64, 4
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    fast = SelfMultiheadAttn(e, h, impl="fast")
    default = SelfMultiheadAttn(e, h, impl="default")
    params = fast.init(jax.random.PRNGKey(0), x)
    out_fast = fast.apply(params, x, is_training=False)
    out_default = default.apply(params, x, is_training=False)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_default),
                               rtol=1e-5, atol=1e-5)


def test_self_attn_matches_manual_reference(rng):
    s, b, e, h = 16, 2, 64, 4
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    m = SelfMultiheadAttn(e, h, impl="fast")
    params = m.init(jax.random.PRNGKey(0), x)
    out = m.apply(params, x, is_training=False)
    ref = _ref_attention(x, params["params"], h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_self_attn_key_padding_mask(rng):
    s, b, e, h = 16, 2, 64, 4
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    pad = jnp.zeros((b, s), jnp.int32).at[:, 12:].set(1)  # 1 = pad out
    m = SelfMultiheadAttn(e, h, impl="fast")
    params = m.init(jax.random.PRNGKey(0), x)
    out = m.apply(params, x, key_padding_mask=pad, is_training=False)
    ref = _ref_attention(x, params["params"], h, key_padding_mask=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # perturbing a padded key position must not change the output
    x2 = x.at[14].add(3.0)
    out2 = m.apply(params, x2, key_padding_mask=pad, is_training=False)
    np.testing.assert_allclose(np.asarray(out[:12]), np.asarray(out2[:12]),
                               rtol=1e-5, atol=1e-5)


def test_self_attn_additive_mask_matches_binary(rng):
    s, b, e, h = 12, 2, 64, 4
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    binary = jnp.zeros((b, s), jnp.int32).at[:, 9:].set(1)
    additive = jnp.where(binary == 1, -10000.0, 0.0).astype(jnp.float32)
    m_bin = SelfMultiheadAttn(e, h, impl="default")
    m_add = SelfMultiheadAttn(e, h, impl="default", mask_additive=True,
                              bias=True)
    p_bin = m_bin.init(jax.random.PRNGKey(0), x)
    p_add = m_add.init(jax.random.PRNGKey(0), x)
    # graft the same projection weights (bias params are zero-init)
    p_add = jax.tree.map(lambda a: a, p_add)
    p_add["params"]["in_proj_weight"] = p_bin["params"]["in_proj_weight"]
    p_add["params"]["out_proj_weight"] = p_bin["params"]["out_proj_weight"]
    out_bin = m_bin.apply(p_bin, x, key_padding_mask=binary,
                          is_training=False)
    out_add = m_add.apply(p_add, x, key_padding_mask=additive,
                          is_training=False)
    np.testing.assert_allclose(np.asarray(out_bin), np.asarray(out_add),
                               rtol=1e-4, atol=1e-5)


def test_self_attn_norm_add(rng):
    """include_norm_add: output = residual + attn(LN(x)); zero attention
    weights would give back the residual."""
    s, b, e, h = 8, 1, 64, 4
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    m = SelfMultiheadAttn(e, h, include_norm_add=True, impl="fast")
    params = m.init(jax.random.PRNGKey(0), x)
    out = m.apply(params, x, is_training=False)
    # zero the out projection → pure residual
    z = jax.tree.map(lambda a: a, params)
    z["params"]["out_proj_weight"] = jnp.zeros_like(
        z["params"]["out_proj_weight"])
    res = m.apply(z, x, is_training=False)
    np.testing.assert_allclose(np.asarray(res), np.asarray(x), rtol=1e-6)
    assert not np.allclose(np.asarray(out), np.asarray(x))


def test_self_attn_separate_qkv(rng):
    s, b, e, h = 8, 2, 64, 4
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    m = SelfMultiheadAttn(e, h, separate_qkv_params=True, bias=True)
    params = m.init(jax.random.PRNGKey(0), x)
    names = set(params["params"].keys())
    assert {"q_weight", "k_weight", "v_weight", "q_bias"} <= names
    out = m.apply(params, x, is_training=False)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()


def test_encdec_attn(rng):
    s_q, s_k, b, e, h = 8, 12, 2, 64, 4
    q = jnp.asarray(rng.standard_normal((s_q, b, e)), jnp.float32)
    kv = jnp.asarray(rng.standard_normal((s_k, b, e)), jnp.float32)
    m = EncdecMultiheadAttn(e, h, impl="fast")
    params = m.init(jax.random.PRNGKey(0), q, kv)
    out = m.apply(params, q, kv, is_training=False)
    assert out.shape == (s_q, b, e)
    out_default = EncdecMultiheadAttn(e, h, impl="default").apply(
        params, q, kv, is_training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_default),
                               rtol=1e-5, atol=1e-5)


def test_mask_softmax_dropout_func(rng):
    b, h, sq, sk = 2, 4, 8, 8
    scores = jnp.asarray(rng.standard_normal((b * h, sq, sk)), jnp.float32)
    pad = jnp.zeros((b, sk), jnp.int32).at[:, 6:].set(1)
    probs = fast_mask_softmax_dropout_func(False, h, scores, pad, False, 0.0)
    assert probs.shape == scores.shape
    np.testing.assert_allclose(np.asarray(jnp.sum(probs, -1)), 1.0, rtol=1e-5)
    assert np.all(np.asarray(probs.reshape(b, h, sq, sk))[:, :, :, 6:] == 0)


def test_fmha_varlen_matches_per_sequence(rng):
    """Packed [total,3,h,d] attention == per-sequence dense attention."""
    h, d = 2, 64
    lens = [48, 80]
    total = sum(lens)
    qkv = jnp.asarray(rng.standard_normal((total, 3, h, d)), jnp.float32)
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    out = fmha_varlen(qkv, cu, causal=True)
    assert out.shape == (total, h, d)
    start = 0
    for n in lens:
        q = qkv[start:start + n, 0].transpose(1, 0, 2)[None]
        k = qkv[start:start + n, 1].transpose(1, 0, 2)[None]
        v = qkv[start:start + n, 2].transpose(1, 0, 2)[None]
        ref = mha_reference(q, k, v, causal=True)[0].transpose(1, 0, 2)
        np.testing.assert_allclose(np.asarray(out[start:start + n]),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)
        start += n


def test_segment_ids_from_cu_seqlens():
    cu = jnp.asarray([0, 3, 7], jnp.int32)
    seg = segment_ids_from_cu_seqlens(cu, 8)
    np.testing.assert_array_equal(np.asarray(seg), [1, 1, 1, 2, 2, 2, 2, 0])
