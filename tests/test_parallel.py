"""Data-parallel tests on the forced 8-device CPU mesh.

Mirrors the reference's tests/distributed suite: DDP grad averaging
(amp_master_params), SyncBatchNorm 1-GPU vs N-GPU parity
(tests/distributed/synced_batchnorm), LARC, clip_grad.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.contrib.clip_grad import clip_grad_norm
from apex_tpu.parallel import (
    LARC,
    DistributedDataParallel,
    Reducer,
    SyncBatchNorm,
    allreduce_grads,
    broadcast_params,
    sync_batch_stats,
)


def test_allreduce_grads_average(mesh8):
    grads = {"w": jnp.arange(16, dtype=jnp.float32).reshape(8, 2)}

    f = shard_map(
        lambda g: allreduce_grads(g, "dp"),
        mesh=mesh8, in_specs=(P("dp"),), out_specs=P("dp"))
    out = f(grads)
    # every shard becomes the mean over shards, broadcast back
    expect_mean = np.asarray(grads["w"]).reshape(8, 1, 2).mean(0)
    np.testing.assert_allclose(np.asarray(out["w"][0:1]), expect_mean, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["w"][7:8]), expect_mean, rtol=1e-6)


def test_allreduce_predivide_matches_plain_average(mesh8):
    g = {"w": jnp.asarray(np.random.default_rng(0).standard_normal((8, 4)), jnp.float32)}
    plain = shard_map(lambda t: allreduce_grads(t, "dp"),
                      mesh=mesh8, in_specs=(P("dp"),), out_specs=P("dp"))(g)
    pre = shard_map(
        lambda t: allreduce_grads(t, "dp", gradient_predivide_factor=4.0,
                                  allreduce_always_fp32=True),
        mesh=mesh8, in_specs=(P("dp"),), out_specs=P("dp"))(g)
    np.testing.assert_allclose(np.asarray(plain["w"]), np.asarray(pre["w"]), rtol=1e-5)


def test_ddp_delay_allreduce_and_sync(mesh8):
    ddp = DistributedDataParallel(axis_name="dp", delay_allreduce=True)
    g = {"w": jnp.ones((8, 2), jnp.float32)}

    def step(t):
        unsynced = ddp.allreduce(t)  # no-op under delay
        return ddp.sync(unsynced)

    out = shard_map(step, mesh=mesh8, in_specs=(P("dp"),), out_specs=P("dp"))(g)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_broadcast_params(mesh8):
    p = {"w": jnp.arange(8, dtype=jnp.float32).reshape(8, 1)}
    out = shard_map(lambda t: broadcast_params(t, "dp"),
                    mesh=mesh8, in_specs=(P("dp"),), out_specs=P("dp"))(p)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.0)  # rank0 value everywhere


def test_reducer(mesh8):
    r = Reducer("dp")
    p = {"w": jnp.arange(8, dtype=jnp.float32).reshape(8, 1)}
    out = shard_map(lambda t: r.reduce(t), mesh=mesh8,
                    in_specs=(P("dp"),), out_specs=P("dp"))(p)
    np.testing.assert_allclose(np.asarray(out["w"]), 3.5)


def test_broadcast_params_nonzero_root(mesh8):
    p = {"w": jnp.arange(8, dtype=jnp.float32).reshape(8, 1)}
    out = shard_map(lambda t: broadcast_params(t, "dp", root=3),
                    mesh=mesh8, in_specs=(P("dp"),), out_specs=P("dp"))(p)
    np.testing.assert_allclose(np.asarray(out["w"]), 3.0)


def test_broadcast_params_rejects_out_of_range_root(mesh8):
    """ISSUE 3 satellite: an out-of-range root would mask out EVERY rank
    and silently broadcast zeros — validated eagerly instead."""
    p = {"w": jnp.arange(8, dtype=jnp.float32).reshape(8, 1)}
    for root in (8, -1):
        with pytest.raises(ValueError, match="outside axis 'dp' of size 8"):
            shard_map(lambda t: broadcast_params(t, "dp", root=root),
                      mesh=mesh8, in_specs=(P("dp"),),
                      out_specs=P("dp"))(p)


def test_broadcast_params_unbound_axis_is_diagnosable():
    """Called outside shard_map/pmap: a RuntimeError naming the axis and
    the fix, not a raw JAX NameError from the internals."""
    with pytest.raises(RuntimeError, match="axis 'dp' is not bound"):
        broadcast_params({"w": jnp.ones((4,))}, "dp")


def test_reducer_unbound_axis_is_diagnosable():
    with pytest.raises(RuntimeError, match="axis 'dp' is not bound"):
        Reducer("dp").reduce({"w": jnp.ones((4,))})


def test_ddp_pjit_style_end_to_end(mesh8):
    """Replicated params + dp-sharded batch: grads match single-device run."""
    ddp = DistributedDataParallel(axis_name="dp", mesh=mesh8)
    W = jnp.asarray(np.random.default_rng(1).standard_normal((4, 3)), jnp.float32)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((16, 4)), jnp.float32)
    y = jnp.asarray(np.random.default_rng(3).standard_normal((16, 3)), jnp.float32)

    def loss(W, x, y):
        return jnp.mean((x @ W - y) ** 2)

    ref = jax.grad(loss)(W, x, y)
    Wr = ddp.replicate(W)
    xb, yb = ddp.shard_batch((x, y))
    with mesh8:
        g = jax.jit(jax.grad(loss))(Wr, xb, yb)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref), rtol=1e-5, atol=1e-6)


# --- SyncBatchNorm ---------------------------------------------------------


def test_syncbn_matches_global_bn(mesh8, rng):
    """N-rank SyncBN == single-device BN over the full batch
    (tests/distributed/synced_batchnorm parity)."""
    x = jnp.asarray(rng.standard_normal((16, 6, 5)), jnp.float32)  # [N, L, C]

    bn = SyncBatchNorm(axis_name="dp", momentum=0.1)
    variables = bn.init(jax.random.PRNGKey(0), x)

    def fwd(v, xs):
        y, updates = bn.apply(v, xs, mutable=["batch_stats"])
        return y, updates

    y_dist, upd = shard_map(
        functools.partial(fwd, variables), mesh=mesh8,
        in_specs=(P("dp"),), out_specs=(P("dp"), P()))(x)

    bn_local = SyncBatchNorm(momentum=0.1)
    y_ref, upd_ref = bn_local.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_dist), np.asarray(y_ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(upd["batch_stats"]["mean"]),
        np.asarray(upd_ref["batch_stats"]["mean"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(upd["batch_stats"]["var"]),
        np.asarray(upd_ref["batch_stats"]["var"]), rtol=1e-4, atol=1e-5)


def test_syncbn_eval_and_relu(rng):
    x = jnp.asarray(rng.standard_normal((4, 3)), jnp.float32)
    bn = SyncBatchNorm(fuse_relu=True)
    v = bn.init(jax.random.PRNGKey(0), x)
    y = bn.apply(v, x, use_running_average=True)  # running stats: mean 0 var 1
    np.testing.assert_allclose(np.asarray(y), np.maximum(np.asarray(x), 0.0),
                               rtol=1e-5, atol=1e-5)
    assert float(jnp.min(y)) >= 0.0


def test_sync_batch_stats_channels_first(rng):
    x = jnp.asarray(rng.standard_normal((4, 7, 5)), jnp.float32)
    mean, var, n = sync_batch_stats(x, channel_axis=1)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(x).mean((0, 2)),
                               rtol=1e-5, atol=1e-6)
    assert float(n) == 20


def test_convert_syncbn_model():
    import flax.linen as nn
    from apex_tpu.parallel import convert_syncbn_model

    class Net(nn.Module):
        norm: nn.Module = nn.BatchNorm(momentum=0.9)
        @nn.compact
        def __call__(self, x):
            return self.norm(x, use_running_average=False)

    net = Net()
    converted = convert_syncbn_model(net, axis_name="dp")
    assert isinstance(converted.norm, SyncBatchNorm)
    assert converted.norm.axis_name == "dp"
    assert abs(converted.norm.momentum - 0.1) < 1e-6


# --- LARC / clip_grad ------------------------------------------------------


def test_larc_scales_gradient(rng):
    from apex_tpu.optimizers import FusedSGD

    params = {"w": jnp.asarray(rng.standard_normal((8, 8)) * 10, jnp.float32)}
    grads = {"w": jnp.asarray(rng.standard_normal((8, 8)) * 1e-3, jnp.float32)}
    opt = LARC(FusedSGD(lr=0.1), trust_coefficient=0.02, clip=True)
    state = opt.init(params)
    new_params, _ = opt.step(grads, params, state)
    # adaptive lr >> base lr here, so clip=1 → behaves like plain SGD
    plain = FusedSGD(lr=0.1)
    p2, _ = plain.step(grads, params, plain.init(params))
    np.testing.assert_allclose(np.asarray(new_params["w"]), np.asarray(p2["w"]),
                               rtol=1e-6)

    # tiny params, big grads → clipping kicks in (update smaller than SGD)
    params_s = {"w": jnp.asarray(rng.standard_normal((8, 8)) * 1e-3, jnp.float32)}
    grads_b = {"w": jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)}
    state_s = opt.init(params_s)
    new_s, _ = opt.step(grads_b, params_s, state_s)
    upd_larc = np.abs(np.asarray(new_s["w"]) - np.asarray(params_s["w"])).max()
    p3, _ = plain.step(grads_b, params_s, plain.init(params_s))
    upd_sgd = np.abs(np.asarray(p3["w"]) - np.asarray(params_s["w"])).max()
    assert upd_larc < upd_sgd


def test_clip_grad_norm(rng):
    grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((6,), 4.0)}
    total = float(np.sqrt(10 * 9 + 6 * 16))
    clipped, norm = clip_grad_norm(grads, max_norm=1.0)
    assert abs(float(norm) - total) < 1e-4
    new_norm = float(np.sqrt(sum((np.asarray(v) ** 2).sum() for v in clipped.values())))
    assert abs(new_norm - 1.0) < 1e-3
    # under max_norm → unchanged
    clipped2, _ = clip_grad_norm(grads, max_norm=100.0)
    np.testing.assert_allclose(np.asarray(clipped2["a"]), 3.0)
    # inf norm type
    _, inf_norm = clip_grad_norm(grads, 1.0, norm_type=float("inf"))
    assert abs(float(inf_norm) - 4.0) < 1e-6


def test_larc_zero_norm_leaves_grad_untouched(rng):
    """ADVICE r1: the weight-decay fold must be gated on nonzero param AND
    grad norms (reference LARC.py applies wd only inside that branch)."""
    from apex_tpu.optimizers import FusedSGD

    params = {"w": jnp.zeros((4, 4), jnp.float32),          # zero param norm
              "v": jnp.asarray(rng.standard_normal((4, 4)), jnp.float32)}
    grads = {"w": jnp.asarray(rng.standard_normal((4, 4)), jnp.float32),
             "v": jnp.zeros((4, 4), jnp.float32)}           # zero grad norm
    # LARC reads weight decay from the inner optimizer (param-group parity)
    opt = LARC(FusedSGD(lr=0.1, weight_decay=0.5), trust_coefficient=0.02)
    plain = FusedSGD(lr=0.1)
    new_p, _ = opt.step(grads, params, opt.init(params))
    ref_p, _ = plain.step(grads, params, plain.init(params))
    # zero-norm leaves: no wd fold, no trust scaling — exactly plain SGD
    np.testing.assert_allclose(np.asarray(new_p["w"]), np.asarray(ref_p["w"]),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p["v"]), np.asarray(ref_p["v"]),
                               rtol=1e-6)


def test_fp16_utils_helpers(rng):
    from apex_tpu.fp16_utils import (
        master_params_to_model_params,
        model_grads_to_master_grads,
        network_to_half,
        prep_param_lists,
    )

    params = {"w": jnp.asarray(rng.standard_normal((4, 4)), jnp.float32),
              "step": jnp.int32(3)}
    half = network_to_half(params, jnp.bfloat16)
    assert half["w"].dtype == jnp.bfloat16
    assert half["step"].dtype == jnp.int32  # non-float leaves untouched

    model_p, master_p = prep_param_lists(half)
    assert master_p["w"].dtype == jnp.float32
    # masters never alias the model params (fp16util.py master copies)
    assert master_p["w"] is not model_p["w"]

    back = master_params_to_model_params(master_p, model_p)
    assert back["w"].dtype == jnp.bfloat16
    g32 = model_grads_to_master_grads({"w": half["w"]})
    assert g32["w"].dtype == jnp.float32


def test_fast_variance_matches_welford_and_clamps(rng):
    """The one-pass local stats (use_fast_variance=True default, the r5
    ResNet lever) must match the Welford-form stats in fp32 on realistic
    activations, and the clamp must keep variance non-negative in the
    cancellation-prone regime (huge mean, tiny variance) instead of
    propagating a negative into rsqrt -> NaN."""
    x = jnp.asarray(rng.normal(2.0, 3.0, (8, 16, 16, 32)), jnp.float32)
    m_fast, v_fast, n_fast = sync_batch_stats(x, use_fast_variance=True)
    m_ref, v_ref, n_ref = sync_batch_stats(x, use_fast_variance=False)
    np.testing.assert_allclose(np.asarray(m_fast), np.asarray(m_ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v_fast), np.asarray(v_ref),
                               rtol=1e-4, atol=1e-5)
    assert float(n_fast) == float(n_ref) == 8 * 16 * 16

    # cancellation regime: mean ~1e4, std ~1e-2 -> E[x^2]-E[x]^2 is a
    # difference of ~1e8 values; the clamp guarantees var >= 0 (the
    # Welford path stays accurate here, which is why cross-rank merges
    # always use it)
    bad = jnp.asarray(1e4 + rng.normal(0.0, 1e-2, (4, 8, 8, 4)),
                      jnp.float32)
    _, v_bad, _ = sync_batch_stats(bad, use_fast_variance=True)
    assert bool(jnp.all(v_bad >= 0.0)), "clamp must prevent negative var"
    assert bool(jnp.all(jnp.isfinite(
        jax.lax.rsqrt(v_bad + 1e-5)))), "rsqrt must stay finite"
