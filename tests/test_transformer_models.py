"""GPT/BERT end-to-end tests (mirrors tests/L0/run_transformer
test_gpt_minimal.py / test_bert_minimal.py): TP-sharded execution must match
the single-device model bitwise-close when given the same full weights, and
a few training steps must reduce the loss under a dp×tp mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import BertModel, GPTModel

CFG = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
           vocab_size=64, max_sequence_length=16)


def _shard_gpt_params(full, rank, world):
    """Slice a full (world=1) GPT param tree into rank's tp shard."""

    def walk(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        def slc(axis):
            k = leaf.shape[axis] // world
            return jax.lax.dynamic_slice_in_dim(leaf, rank * k, k, axis)

        if "word_embeddings" in name and name.endswith("embedding"):
            return slc(0)
        if ("query_key_value" in name or "dense_h_to_4h" in name):
            return slc(1) if name.endswith("kernel") else slc(0)
        if name.endswith("dense/kernel") or name.endswith("dense_4h_to_h/kernel"):
            return slc(0)
        return leaf

    return jax.tree_util.tree_map_with_path(walk, full)


@pytest.fixture
def tp4_mesh(devices):
    mesh = parallel_state.initialize_model_parallel(4, 1, devices=devices[:4])
    yield mesh
    parallel_state.destroy_model_parallel()


@pytest.fixture
def dp2tp4_mesh(devices):
    mesh = parallel_state.initialize_model_parallel(4, 1, devices=devices[:8])
    yield mesh
    parallel_state.destroy_model_parallel()


# SP=True is the stronger variant (exercises every SP mapping on top of
# TP); the SP=False collective plan is pinned by test_tensor_parallel and
# test_hlo_comm_plan, so one full-model run suffices for suite wall time.
# slow: grad-of-shard_map over the full model is a ~26 s XLA-CPU compile
# — the tp/sp mappings stay covered in tier-1 by test_tensor_parallel +
# test_hlo_comm_plan; this whole-model bitwise run rides the slow tier
@pytest.mark.slow
@pytest.mark.parametrize("sp", [True])
def test_gpt_tp_matches_single_device(tp4_mesh, rng, sp):
    """Same full weights: tp=4 (±sequence parallel) loss/grads == world-1 run."""
    ids = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    ref_model = GPTModel(**CFG)
    full = ref_model.init(jax.random.PRNGKey(0), ids)
    ref_loss = ref_model.apply(full, ids, labels=ids).mean()
    ref_grads = jax.grad(
        lambda p: ref_model.apply(p, ids, labels=ids).mean())(full)

    tp_model = GPTModel(**CFG, sequence_parallel_enabled=sp)

    def run(full, ids):
        rank = jax.lax.axis_index("tp")
        shard = _shard_gpt_params(full, rank, 4)
        loss = tp_model.apply(shard, ids, labels=ids).mean()
        g = jax.grad(lambda p: tp_model.apply(p, ids, labels=ids).mean())(shard)
        # compare a column-parallel kernel grad: gather to full
        gk = jax.lax.all_gather(
            g["params"]["language_model"]["transformer"]["layer_0"]
             ["self_attention"]["query_key_value"]["kernel"],
            "tp", axis=1, tiled=True)
        # and the (replicated) layernorm grad
        gln = g["params"]["language_model"]["transformer"]["final_layernorm"]["scale"]
        return loss, gk, gln

    loss, gk, gln = shard_map(
        run, mesh=tp4_mesh, in_specs=(P(), P()),
        out_specs=(P(), P(None), P(None)), **NO_REP_CHECK)(full, ids)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    rk = ref_grads["params"]["language_model"]["transformer"]["layer_0"][
        "self_attention"]["query_key_value"]["kernel"]
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=1e-4, atol=1e-5)
    rln = ref_grads["params"]["language_model"]["transformer"]["final_layernorm"]["scale"]
    np.testing.assert_allclose(np.asarray(gln), np.asarray(rln), rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # 8-step dp×tp trajectory: ~11 s compile on XLA-CPU
def test_gpt_trains_on_dp_tp_mesh(dp2tp4_mesh, rng):
    """GPT minimal training parity: dp=2 × tp=4 from the same full weights must
    reproduce the single-device loss trajectory step for step, and the loss
    must decrease (test_gpt_minimal, strengthened from a drop-% threshold to a
    trajectory-parity assertion)."""
    from apex_tpu.optimizers import FusedAdam

    model = GPTModel(**CFG)
    opt = FusedAdam(lr=1e-3)
    ids = jnp.asarray(rng.integers(0, 64, (4, 16)), jnp.int32)
    full = model.init(jax.random.PRNGKey(0), ids)

    # single-device reference trajectory (batch 4 == dp-mean of two halves)
    @jax.jit
    def ref_step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(
            lambda p: model.apply(p, ids, labels=ids).mean())(params)
        new_params, new_state = opt.step(grads, params, opt_state)
        return new_params, new_state, loss

    ref_params, ref_state = full, opt.init(full)
    ref_losses = []
    for _ in range(8):
        ref_params, ref_state, loss = ref_step(ref_params, ref_state, ids)
        ref_losses.append(float(loss))

    def init_fn(full):
        shard = _shard_gpt_params(full, jax.lax.axis_index("tp"), 4)
        return shard, opt.init(shard)

    def step(params, opt_state, ids):
        def loss_fn(p):
            return model.apply(p, ids, labels=ids).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # dp grad sync + dp-mean loss
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        loss = jax.lax.pmean(loss, "dp")
        new_params, new_state = opt.step(grads, params, opt_state)
        return new_params, new_state, loss

    with dp2tp4_mesh:
        params, opt_state = shard_map(
            init_fn, mesh=dp2tp4_mesh, in_specs=(P(),),
            out_specs=P(), **NO_REP_CHECK)(full)
        # params replicated over dp, sharded over tp (per-rank views).
        # jax.jit on top of shard_map is essential: a bare shard_map call
        # re-traces and re-compiles every invocation (~40s/step on CPU).
        step_m = jax.jit(shard_map(
            step, mesh=dp2tp4_mesh,
            in_specs=(P(), P(), P("dp")), out_specs=(P(), P(), P()),
            **NO_REP_CHECK))
        losses = []
        for _ in range(8):
            params, opt_state, loss = step_m(params, opt_state, ids)
            losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4)
    assert losses[-1] < losses[0], losses


def test_bert_forward_and_masking(rng):
    """BERT padding-mask semantics: masked positions don't affect outputs of
    kept positions (test_bert_minimal behavior check)."""
    model = BertModel(**CFG)
    ids = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32).at[:, 12:].set(0)
    params = model.init(jax.random.PRNGKey(0), ids, mask)
    logits, binary = model.apply(params, ids, mask)
    assert logits.shape == (16, 2, 64)
    assert binary.shape == (2, 2)
    # changing a masked-out token must not change kept-position logits
    ids2 = ids.at[:, 14].set((ids[:, 14] + 1) % 64)
    logits2, _ = model.apply(params, ids2, mask)
    np.testing.assert_allclose(np.asarray(logits[:12]), np.asarray(logits2[:12]),
                               rtol=1e-4, atol=1e-5)


def test_gpt_rope_variant(rng):
    model = GPTModel(**CFG, apply_rope=True)
    ids = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    # rope model has no position table
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    names = ["/".join(str(p.key) for p in path if hasattr(p, "key"))
             for path, _ in flat]
    assert not any("position_embeddings" in n for n in names)
    loss = model.apply(params, ids, labels=ids)
    assert np.isfinite(np.asarray(loss)).all()


# the plain remat flag stays in tier-1; each named policy is another
# whole-model compile (~3 s) re-proving the same loss-parity claim and
# rides the slow tier
@pytest.mark.parametrize("kwargs", [
    dict(activations_checkpoint=True),
    pytest.param(dict(activations_checkpoint_policy="dots"),
                 marks=pytest.mark.slow),
    pytest.param(dict(activations_checkpoint_policy="dots_no_batch"),
                 marks=pytest.mark.slow),
    pytest.param(dict(activations_checkpoint_policy="except_activations"),
                 marks=pytest.mark.slow),
])
def test_gpt_activation_checkpointing_same_loss(rng, kwargs):
    ids = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    m1 = GPTModel(**CFG)
    m2 = GPTModel(**CFG, **kwargs)
    p = m1.init(jax.random.PRNGKey(0), ids)
    l1 = m1.apply(p, ids, labels=ids).mean()
    l2 = m2.apply(p, ids, labels=ids).mean()
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.grad(lambda p: m1.apply(p, ids, labels=ids).mean())(p)
    g2 = jax.grad(lambda p: m2.apply(p, ids, labels=ids).mean())(p)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
