"""Native (C++) host flatten/unflatten vs the numpy fallback."""

import numpy as np
import pytest

from apex_tpu.utils import _native
from apex_tpu.utils.packing import (
    host_flatten_dense_tensors,
    host_unflatten_dense_tensors,
)


def _arrays(rng, dtype=np.float32):
    return [rng.standard_normal((4, 8)).astype(dtype),
            rng.standard_normal((16,)).astype(dtype),
            rng.standard_normal((2, 3, 5)).astype(dtype)]


def test_native_library_builds():
    # g++ is part of this environment's toolchain; the native path must
    # actually build here (the numpy fallback exists for machines without)
    assert _native.lib() is not None


def test_host_flatten_roundtrip():
    rng = np.random.default_rng(0)
    arrays = _arrays(rng)
    flat = host_flatten_dense_tensors(arrays)
    assert flat.shape == (sum(a.size for a in arrays),)
    np.testing.assert_array_equal(
        flat, np.concatenate([a.ravel() for a in arrays]))
    back = host_unflatten_dense_tensors(flat, arrays)
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32])
def test_host_flatten_dtypes(dtype):
    rng = np.random.default_rng(1)
    arrays = [rng.standard_normal((8, 8)).astype(dtype),
              rng.standard_normal((3,)).astype(dtype)]
    flat = host_flatten_dense_tensors(arrays)
    assert flat.dtype == dtype
    back = host_unflatten_dense_tensors(flat, arrays)
    np.testing.assert_array_equal(back[0], arrays[0])


def test_native_matches_numpy_fallback(monkeypatch):
    rng = np.random.default_rng(2)
    arrays = _arrays(rng)
    native = host_flatten_dense_tensors(arrays)
    monkeypatch.setattr(_native, "lib", lambda: None)
    fallback = host_flatten_dense_tensors(arrays)
    np.testing.assert_array_equal(native, fallback)


def test_short_flat_buffer_rejected():
    # both the native and fallback paths must refuse, not read past the end
    with pytest.raises(ValueError):
        host_unflatten_dense_tensors(np.zeros(10, np.float32),
                                     [np.empty((4, 8), np.float32)])


def test_mixed_dtype_rejected():
    with pytest.raises(ValueError):
        host_flatten_dense_tensors([np.zeros(3, np.float32),
                                    np.zeros(3, np.float16)])


def test_non_contiguous_inputs():
    rng = np.random.default_rng(3)
    base = rng.standard_normal((8, 8)).astype(np.float32)
    view = base[::2, ::2]  # non-contiguous
    flat = host_flatten_dense_tensors([view])
    np.testing.assert_array_equal(flat, view.ravel())
