"""Fused-optimizer parity tests vs. pure reference implementations.

Mirrors tests/L0/run_optimizers/test_fused_optimizer.py in the reference:
numerical comparison of the fused path against a trusted implementation
(there: torch.optim; here: optax / hand-written numpy) across dtypes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedMixedPrecisionLamb,
    FusedNovoGrad,
    FusedSGD,
)


def _params(rng, dtype=jnp.float32):
    return {
        "w": jnp.asarray(rng.standard_normal((17, 23)), dtype),
        "b": jnp.asarray(rng.standard_normal((23,)), dtype),
    }


def _grads_like(rng, params):
    return jax.tree.map(lambda p: jnp.asarray(rng.standard_normal(p.shape), p.dtype), params)


def run_steps(opt, params, grad_seq, **kw):
    state = opt.init(params)
    for g in grad_seq:
        params, state = opt.step(g, params, state, **kw)
    return params, state


class TestFusedAdam:
    @pytest.mark.parametrize("adam_w", [True, False])
    def test_vs_optax(self, rng, adam_w):
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(5)]
        lr, wd = 1e-2, 0.1
        fused = FusedAdam(lr=lr, weight_decay=wd, adam_w_mode=adam_w, eps=1e-8)
        got, _ = run_steps(fused, params, grads)

        if adam_w:
            ref_opt = optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)
        else:
            # adam with L2 folded into the gradient
            ref_opt = optax.chain(optax.add_decayed_weights(wd), optax.adam(lr, eps=1e-8))
        rp, rs = params, ref_opt.init(params)
        for g in grads:
            upd, rs = ref_opt.update(g, rs, rp)
            rp = optax.apply_updates(rp, upd)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6), got, rp
        )

    def test_skip_on_overflow(self, rng):
        params = _params(rng)
        opt = FusedAdam(lr=0.1)
        state = opt.init(params)
        g = _grads_like(rng, params)
        inf_flag = jnp.ones((), jnp.bool_)
        new_params, new_state = opt.step(g, params, state, found_inf=inf_flag)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), new_params, params)
        assert int(new_state[0].step) == 0  # step not advanced on skip

    def test_grad_scale(self, rng):
        params = _params(rng)
        g = _grads_like(rng, params)
        opt = FusedAdam(lr=0.1)
        p1, _ = run_steps(opt, params, [g])
        scaled = jax.tree.map(lambda x: x * 64.0, g)
        p2, _ = run_steps(opt, params, [scaled], grad_scale=jnp.float32(64.0))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), p1, p2)

    def test_master_weights_bf16(self, rng):
        params = _params(rng, jnp.bfloat16)
        grads = [_grads_like(rng, params) for _ in range(20)]
        opt_m = FusedAdam(lr=1e-2, master_weights=True)
        opt_n = FusedAdam(lr=1e-2, master_weights=False)
        pm, sm = run_steps(opt_m, params, grads)
        pn, _ = run_steps(opt_n, params, grads)
        # master path must track the fp32 trajectory more closely
        p32, _ = run_steps(FusedAdam(lr=1e-2), jax.tree.map(lambda x: x.astype(jnp.float32), params),
                           [jax.tree.map(lambda g: g.astype(jnp.float32), g) for g in grads])
        err_m = float(jnp.abs(sm[1].master_params["w"] - p32["w"]).max())
        err_n = float(jnp.abs(pn["w"].astype(jnp.float32) - p32["w"]).max())
        assert err_m < err_n
        assert pm["w"].dtype == jnp.bfloat16

    def test_amsgrad_rejected(self):
        with pytest.raises(RuntimeError):
            FusedAdam(amsgrad=True)

    def test_adam_bf16_state_parity(self, rng):
        """state_dtype=bf16 tracks the fp32-state trajectory (same contract
        as test_lamb_bf16_state_parity — the lever that fits the llama-1b
        bench config's Adam moments in 16 GB HBM)."""
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(10)]
        kw = dict(lr=1e-2, weight_decay=0.01)
        ref, (ref_inner, _) = run_steps(FusedAdam(**kw), params, grads)
        got, (got_inner, _) = run_steps(
            FusedAdam(state_dtype=jnp.bfloat16, **kw), params, grads)
        assert got_inner.exp_avg["w"].dtype == jnp.bfloat16
        assert got_inner.exp_avg_sq["w"].dtype == jnp.bfloat16
        assert ref_inner.exp_avg["w"].dtype == jnp.float32
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-3),
            got, ref)
        da = np.ravel(np.asarray(got["w"] - params["w"], np.float64))
        db = np.ravel(np.asarray(ref["w"] - params["w"], np.float64))
        cos = da @ db / (np.linalg.norm(da) * np.linalg.norm(db))
        assert cos > 0.999


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd", [(0.0, False, 0.0), (0.9, False, 1e-4), (0.9, True, 0.0)])
    def test_vs_optax(self, rng, momentum, nesterov, wd):
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(5)]
        fused = FusedSGD(lr=0.05, momentum=momentum, nesterov=nesterov, weight_decay=wd)
        got, _ = run_steps(fused, params, grads)

        ref_opt = optax.chain(
            optax.add_decayed_weights(wd) if wd else optax.identity(),
            optax.sgd(0.05, momentum=momentum or None, nesterov=nesterov),
        )
        rp, rs = params, ref_opt.init(params)
        for g in grads:
            upd, rs = ref_opt.update(g, rs, rp)
            rp = optax.apply_updates(rp, upd)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7), got, rp)


class TestFusedAdagrad:
    def test_vs_reference(self, rng):
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(4)]
        lr, eps = 0.1, 1e-10
        got, _ = run_steps(FusedAdagrad(lr=lr, eps=eps), params, grads)
        # hand reference
        p = {k: np.asarray(v, np.float64) for k, v in params.items()}
        h = {k: np.zeros_like(v) for k, v in p.items()}
        for g in grads:
            for k in p:
                gk = np.asarray(g[k], np.float64)
                h[k] += gk * gk
                p[k] -= lr * gk / (np.sqrt(h[k]) + eps)
        for k in p:
            np.testing.assert_allclose(got[k], p[k], rtol=1e-5)


class TestFusedLAMB:
    def test_trust_ratio_and_clip(self, rng):
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(3)]
        lr, wd, eps, mgn = 1e-2, 0.01, 1e-6, 1.0
        got, _ = run_steps(FusedLAMB(lr=lr, weight_decay=wd, eps=eps, max_grad_norm=mgn), params, grads)

        # hand reference mirroring multi_tensor_lamb.cu
        p = {k: np.asarray(v, np.float64) for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in p.items()}
        v = {k: np.zeros_like(v_) for k, v_ in p.items()}
        b1, b2 = 0.9, 0.999
        for t, g in enumerate(grads, start=1):
            gnorm = np.sqrt(sum(np.sum(np.asarray(g[k], np.float64) ** 2) for k in p))
            clip = max(gnorm / mgn, 1.0)
            bc1, bc2 = 1 - b1**t, 1 - b2**t
            for k in p:
                gk = np.asarray(g[k], np.float64) / clip
                m[k] = b1 * m[k] + (1 - b1) * gk
                v[k] = b2 * v[k] + (1 - b2) * gk * gk
                upd = (m[k] / bc1) / (np.sqrt(v[k] / bc2) + eps) + wd * p[k]
                pn, un = np.linalg.norm(p[k]), np.linalg.norm(upd)
                ratio = pn / un if pn > 0 and un > 0 else 1.0
                p[k] -= lr * ratio * upd
        for k in p:
            np.testing.assert_allclose(got[k], p[k], rtol=1e-4, atol=1e-7)

    def test_lamb_bf16_state_parity(self, rng):
        """state_dtype=bf16 tracks the fp32-state trajectory.

        The reduced-precision moments round at ~2^-8 relative per step;
        over 10 steps at lr=1e-2 the parameter trajectories must agree to
        ~1e-2 relative — the contract that makes the 1.3B single-chip
        configuration (bench.py --model 1.3b) a faithful LAMB run and not
        a different optimizer.
        """
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(10)]
        kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        ref, (ref_inner, _) = run_steps(FusedLAMB(**kw), params, grads)
        got, (got_inner, _) = run_steps(
            FusedLAMB(state_dtype=jnp.bfloat16, **kw), params, grads)
        assert got_inner.exp_avg["w"].dtype == jnp.bfloat16
        assert got_inner.exp_avg_sq["w"].dtype == jnp.bfloat16
        assert ref_inner.exp_avg["w"].dtype == jnp.float32
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-3),
            got, ref)
        # and it must not silently BE the fp32 path: states differ in dtype
        # but the update direction is preserved (cosine ~ 1)
        da = np.ravel(np.asarray(got["w"] - params["w"], np.float64))
        db = np.ravel(np.asarray(ref["w"] - params["w"], np.float64))
        cos = da @ db / (np.linalg.norm(da) * np.linalg.norm(db))
        assert cos > 0.999

    def test_packed_rejects_state_dtype(self):
        with pytest.raises(ValueError):
            FusedLAMB(packed=True, state_dtype=jnp.bfloat16)


class TestFusedNovoGrad:
    def test_basic_math(self, rng):
        params = _params(rng)
        grads = [_grads_like(rng, params) for _ in range(3)]
        lr, eps = 1e-2, 1e-8
        b1, b2 = 0.95, 0.98
        got, _ = run_steps(FusedNovoGrad(lr=lr, betas=(b1, b2), eps=eps, bias_correction=False), params, grads)
        p = {k: np.asarray(v, np.float64) for k, v in params.items()}
        m = {k: np.zeros_like(v) for k, v in p.items()}
        vs = {k: 0.0 for k in p}
        for t, g in enumerate(grads, start=1):
            for k in p:
                gk = np.asarray(g[k], np.float64)
                gsq = np.sum(gk * gk)
                vs[k] = gsq if t == 1 else b2 * vs[k] + (1 - b2) * gsq
                ghat = gk / (np.sqrt(vs[k]) + eps)
                m[k] = b1 * m[k] + ghat
                p[k] -= lr * m[k]
        for k in p:
            np.testing.assert_allclose(got[k], p[k], rtol=1e-5, atol=1e-7)


class TestFusedMixedPrecisionLamb:
    def test_runs_and_updates(self, rng):
        params = _params(rng, jnp.bfloat16)
        opt = FusedMixedPrecisionLamb(lr=1e-2)
        state = opt.init(params)
        g = _grads_like(rng, params)
        new_p, new_s = opt.step(g, params, state)
        assert new_p["w"].dtype == jnp.bfloat16
        assert int(new_s[0].step) == 1
        assert float(jnp.abs(new_p["w"].astype(jnp.float32) - params["w"].astype(jnp.float32)).max()) > 0

    def test_device_lr(self, rng):
        params = _params(rng)
        opt = FusedMixedPrecisionLamb(lr=1e-2, master_weights=False)
        state = opt.init(params)
        state = opt.set_lr(state, 0.0)
        g = _grads_like(rng, params)
        new_p, _ = opt.step(g, params, state)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), new_p, params)


def test_as_optax_adapter(rng):
    params = _params(rng)
    opt = FusedAdam(lr=1e-2).as_optax()
    state = opt.init(params)
    g = _grads_like(rng, params)
    upd, state = opt.update(g, state, params)
    new_p = optax.apply_updates(params, upd)
    direct, _ = FusedAdam(lr=1e-2).step(g, params, FusedAdam(lr=1e-2).init(params))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), new_p, direct)
