"""LossScaler behavior tests.

Mirrors the reference's scaler behavior (apex/amp/scaler.py:33-217) and the
hysteresis kernel test (tests/L0/run_amp/test_update_scale_hysteresis.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp.scaler import LossScaler, static_loss_scaler


def test_defaults_match_reference():
    s = LossScaler()
    st = s.init()
    assert float(st.scale) == 2.0**16
    assert s.growth_interval == 2000
    assert s.backoff_factor == 0.5


def test_scale_and_unscale():
    s = LossScaler(init_scale=8.0)
    st = s.init()
    loss = jnp.float32(2.0)
    assert float(s.scale_loss(loss, st)) == 16.0
    grads = {"w": jnp.full((4,), 8.0)}
    unscaled, found_inf = s.unscale(grads, st)
    np.testing.assert_allclose(unscaled["w"], 1.0)
    assert not bool(found_inf)


def test_backoff_on_overflow():
    s = LossScaler(init_scale=2.0**10)
    st = s.init()
    grads = {"w": jnp.asarray([jnp.inf, 1.0])}
    _, found_inf = s.unscale(grads, st)
    assert bool(found_inf)
    st2 = s.update(st, found_inf)
    assert float(st2.scale) == 2.0**9
    assert int(st2.growth_tracker) == 0
    assert int(st2.unskipped) == 0


def test_growth_after_interval():
    s = LossScaler(init_scale=4.0, growth_interval=3)
    st = s.init()
    ok = jnp.zeros((), jnp.bool_)
    for _ in range(2):
        st = s.update(st, ok)
        assert float(st.scale) == 4.0
    st = s.update(st, ok)
    assert float(st.scale) == 8.0
    assert int(st.growth_tracker) == 0


def test_hysteresis():
    # With hysteresis=2 the first overflow must NOT back off, the second must
    # (csrc/update_scale_hysteresis.cu semantics).
    s = LossScaler(init_scale=16.0, hysteresis=2)
    st = s.init()
    bad = jnp.ones((), jnp.bool_)
    st = s.update(st, bad)
    assert float(st.scale) == 16.0
    st = s.update(st, bad)
    assert float(st.scale) == 8.0


def test_min_max_clamp():
    s = LossScaler(init_scale=2.0, min_loss_scale=1.0, growth_interval=1, max_loss_scale=4.0)
    st = s.init()
    bad = jnp.ones((), jnp.bool_)
    st = s.update(st, bad)
    st = s.update(st, bad)
    assert float(st.scale) == 1.0  # clamped at min
    ok = jnp.zeros((), jnp.bool_)
    st = s.update(st, ok)
    st = s.update(st, ok)
    st = s.update(st, ok)
    assert float(st.scale) == 4.0  # clamped at max


def test_static_scaler_never_moves():
    s = static_loss_scaler(128.0)
    st = s.init()
    st = s.update(st, jnp.ones((), jnp.bool_))
    st = s.update(st, jnp.zeros((), jnp.bool_))
    assert float(st.scale) == 128.0
    assert int(st.unskipped) == 1


def test_update_is_jittable():
    s = LossScaler()
    st = s.init()
    st2 = jax.jit(s.update)(st, jnp.zeros((), jnp.bool_))
    assert int(st2.growth_tracker) == 1


def test_state_dict_roundtrip():
    s = LossScaler()
    st = s.update(s.init(), jnp.ones((), jnp.bool_))
    d = s.state_dict(st)
    st2 = s.load_state_dict(d)
    assert float(st2.scale) == float(st.scale)
    assert int(st2.hysteresis_tracker) == int(st.hysteresis_tracker)


def test_hysteresis_resets_on_clean_steps():
    """Isolated overflows must not ratchet the scale down: the CUDA kernel
    resets the hysteresis tracker on every clean step
    (csrc/update_scale_hysteresis.cu "Reset the hysteresis tracker")."""
    import jax.numpy as jnp
    from apex_tpu.amp.scaler import LossScaler

    s = LossScaler(init_scale=2.0**16, hysteresis=2)
    st = s.init()
    inf, ok = jnp.bool_(True), jnp.bool_(False)
    st = s.update(st, inf)          # burns 1 hysteresis, no backoff
    assert float(st.scale) == 2.0**16
    for _ in range(5):
        st = s.update(st, ok)       # clean steps reset the tracker
    st = s.update(st, inf)          # isolated overflow again: still no backoff
    assert float(st.scale) == 2.0**16
    st = s.update(st, inf)          # consecutive overflow: now back off
    assert float(st.scale) == 2.0**15


def test_growth_clamped_at_default_max():
    """Growth must clamp at the reference default max_loss_scale=2**24
    (apex/amp/scaler.py) — from one doubling below, and then stay put."""
    s = LossScaler(init_scale=2.0**23, growth_interval=1)
    st = s.init()
    ok = jnp.zeros((), jnp.bool_)
    st = s.update(st, ok)
    assert float(st.scale) == 2.0**24
    for _ in range(3):
        st = s.update(st, ok)
        assert float(st.scale) == 2.0**24  # clamped, not growing past max
        assert int(st.growth_tracker) == 0


def test_backoff_clamped_at_min_loss_scale():
    """Backoff must clamp at min_loss_scale: from 1.5x the floor one
    overflow lands ON the floor (max(0.75*min... ) rule), and further
    overflows cannot push below it."""
    s = LossScaler(init_scale=3.0, min_loss_scale=2.0)
    st = s.init()
    bad = jnp.ones((), jnp.bool_)
    st = s.update(st, bad)
    assert float(st.scale) == 2.0  # 1.5 would be below the floor
    for _ in range(3):
        st = s.update(st, bad)
        assert float(st.scale) == 2.0


def test_hysteresis_tolerates_exactly_h_minus_1_overflows():
    """hysteresis=h must tolerate exactly h-1 *consecutive* overflows
    before backing off — the h-th burns the budget
    (csrc/update_scale_hysteresis.cu decrement-then-test order)."""
    h = 3
    s = LossScaler(init_scale=2.0**12, hysteresis=h)
    st = s.init()
    bad = jnp.ones((), jnp.bool_)
    for i in range(h - 1):
        st = s.update(st, bad)
        assert float(st.scale) == 2.0**12, f"backed off after {i+1} < h overflows"
        assert int(st.hysteresis_tracker) == h - 1 - i
    st = s.update(st, bad)  # the h-th consecutive overflow
    assert float(st.scale) == 2.0**11
    # a clean step restores the full budget, so h-1 overflows pass again
    st = s.update(st, jnp.zeros((), jnp.bool_))
    for _ in range(h - 1):
        st = s.update(st, bad)
    assert float(st.scale) == 2.0**11


def test_unscale_returns_fp32():
    """Unscaling must not happen in fp16 (subnormal flush)."""
    import jax.numpy as jnp
    from apex_tpu.amp.scaler import LossScaler

    s = LossScaler(init_scale=2.0**16)
    st = s.init()
    grads = {"w": jnp.full((4,), 2e-3, jnp.float16)}
    unscaled, found = s.unscale(grads, st)
    assert unscaled["w"].dtype == jnp.float32
    expect = float(jnp.float16(2e-3)) / 2.0**16  # fp16-rounded input, fp32 math
    assert abs(float(unscaled["w"][0]) - expect) < 1e-12
    assert float(unscaled["w"][0]) > 0.0  # would flush to 0 in fp16 math
    assert not bool(found)
