"""amp policy + initialize behavior tests.

Mirrors tests/L0/run_amp/test_basic_casts.py / test_checkpointing.py style:
policy semantics per opt level, input/param casting, checkpoint roundtrip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


def apply_fn(params, x):
    return x @ params["w"] + params["b"]


def make_params():
    return {
        "w": jnp.ones((4, 3), jnp.float32),
        "b": jnp.zeros((3,), jnp.float32),
        "norm_scale": jnp.ones((3,), jnp.float32),
    }


def test_o0_identity():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O0")
    assert amped.params["w"].dtype == jnp.float32
    out = amped.apply(amped.params, jnp.ones((2, 4), jnp.float32))
    assert out.dtype == jnp.float32


def test_o1_keeps_params_fp32():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O1")
    assert amped.params["w"].dtype == jnp.float32
    assert amped.policy.compute_dtype == jnp.bfloat16


def test_o2_casts_params_keeps_norms():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O2")
    assert amped.params["w"].dtype == jnp.bfloat16
    # keep_batchnorm_fp32 analog: norm-like params stay fp32
    assert amped.params["norm_scale"].dtype == jnp.float32
    assert amped.policy.master_weights
    out = amped.apply(amped.params, jnp.ones((2, 4), jnp.float32))
    assert out.dtype == jnp.bfloat16


def test_o3_casts_everything():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O3")
    assert amped.params["norm_scale"].dtype == jnp.bfloat16


def test_fp16_gets_dynamic_scaler():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O2", half_dtype=jnp.float16)
    assert amped.scaler.dynamic
    assert float(amped.scaler_state.scale) == 2.0**16


def test_bf16_gets_unit_static_scale():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O2")
    assert not amped.scaler.dynamic
    assert float(amped.scaler_state.scale) == 1.0


def test_explicit_loss_scale_override():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O2", loss_scale=128.0)
    assert float(amped.scaler_state.scale) == 128.0


def test_bad_level_raises():
    with pytest.raises(ValueError):
        amp.initialize(apply_fn, make_params(), opt_level="O4")


def test_state_dict_roundtrip():
    amped = amp.initialize(apply_fn, make_params(), opt_level="O2",
                           half_dtype=jnp.float16, num_losses=2)
    d = amp.state_dict(amped)
    assert set(d) == {"loss_scaler0", "loss_scaler1"}
    amped2 = amp.load_state_dict(amped, d)
    assert float(amped2.scaler_states[1].scale) == float(amped.scaler_states[1].scale)


def test_end_to_end_bf16_training_step(rng):
    """A minimal amp-style train step in bf16 (the README pattern)."""
    from apex_tpu.optimizers import FusedSGD

    params = make_params()
    amped = amp.initialize(apply_fn, params, opt_level="O2")
    opt = FusedSGD(lr=0.1, master_weights=True)
    opt_state = opt.init(amped.params)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((8, 3)), jnp.float32)

    def loss_fn(p):
        pred = amped.apply(p, x)
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(amped.params)
    new_params, opt_state = opt.step(grads, amped.params, opt_state)
    loss2 = loss_fn(new_params)
    assert float(loss2) < float(loss)
