"""``tools/bench_compare.py`` — bench-round regression diffing
(ISSUE 12 satellite): golden fixtures for every classification family,
tolerance semantics, and the nonzero exit code on regression."""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import bench_compare as bc  # noqa: E402

# a miniature bench result exercising every classification family
GOLDEN_OLD = {
    "metric": "llama_tokens_per_sec_per_chip",
    "value": 1000.0,
    "step_time_ms": 50.0,
    "serving": {
        "ok": True,
        "decode_ms_per_token": 4.0,
        "throughput_tokens_per_s": {"4": 200.0},
        "speedup_4_vs_sequential": 3.0,
        "decode_compiles_after_warmup": 1,
        "config": {"slots": 8},
    },
    "serving_slo": {
        "ok": True,
        "loads": {"2x": {"ttft_s": {"p99": 0.10, "n": 24},
                         "goodput": 0.8}},
    },
    "serving_reload": {
        "ok": True,
        "reload_wall_s": 0.5,
        "swap_pause_ms": 2.0,
        "dropped_streams": 0,
        "ab_mirror_overhead_ratio": 1.05,
        "decode_compiles_after_warmup": 1,
        "config": {"reload_at_step": 4},
    },
    "serving_fleet": {
        "ok": True,
        "failover_latency_s": 0.02,
        "throughput_vs_baseline": 0.7,
        "goodput_delta": 0.3,
        "dropped_streams": 0,
        "shed": 0,
        "resumed": 3,
        "decode_compiles": 3,
        "config": {"kill_step": 4},
    },
    "serving_quant": {
        "ok": True,
        "agreement": 1.0,
        "max_logit_error": 0.04,
        "capacity_ratio": 3.84,
        "fp32": {"decode_ms_per_token": 4.0,
                 "kv_bytes_per_token": 4608.0,
                 "decode_compiles": 1},
        "int8": {"decode_ms_per_token": 4.1,
                 "kv_bytes_per_token": 1200.0,
                 "decode_compiles": 1},
        "agreement_ok": True,
        "capacity_ok": True,
        "config": {"slots": 4},
    },
    "serving_rollout": {
        "ok": True,
        "replicas": 3,
        "rollout_wall_s": 1.5,
        "swap_pause_s_max": 0.001,
        "swap_pause_s_mean": 0.0008,
        "verdict_latency_s": 0.2,
        "dropped_streams": 0,
        "halts": 0,
        "rollbacks": 0,
        "shed": 0,
        "canary_completed": 2,
        "decode_compiles": 3,
        "config": {"canary_window_steps": 16},
    },
    "obs_fleet": {
        "ok": True,
        "bare_wall_s": 0.6,
        "instrumented_wall_s": 0.63,
        "overhead_ratio": 1.05,
        "alert_eval_us_per_step": 80.0,
        "trace_export_ms": 0.9,
        "alerts_firing": 1,
        "alert_transitions": 1,
        "traced_requests": 18,
        "decode_compiles": 3,
        "config": {"n_rules": 32},
    },
}


def _mutated(**paths):
    """Deep-copy the golden with dotted-path overrides."""
    new = json.loads(json.dumps(GOLDEN_OLD))
    for dotted, value in paths.items():
        parts = dotted.split(".")
        node = new
        for p in parts[:-1]:
            node = node[p]
        node[parts[-1]] = value
    return new


def _kinds(findings):
    return {f.path: f.kind for f in findings}


class TestClassify:
    def test_families(self):
        assert bc.classify("serving.throughput_tokens_per_s.4") == "higher"
        assert bc.classify("value") == "higher"
        assert bc.classify("serving.speedup_4_vs_sequential") == "higher"
        assert bc.classify("serving_slo.loads.2x.goodput") == "higher"
        assert bc.classify("mfu") == "higher"
        assert bc.classify("step_time_ms") == "lower"
        assert bc.classify("serving.decode_ms_per_token") == "lower"
        assert bc.classify("serving_slo.loads.2x.ttft_s.p99") == "lower"
        assert bc.classify("slo.queue_wait_s.p95") == "lower"
        assert bc.classify("serving.decode_compiles_after_warmup") == "exact"
        assert bc.classify("serving.ok") == "exact_higher"

    def test_reload_family_direction_aware(self):
        base = "serving_reload"
        assert bc.classify(f"{base}.ok") == "exact_higher"
        assert bc.classify(f"{base}.swap_pause_ms") == "lower"
        assert bc.classify(f"{base}.reload_wall_s") == "lower"
        assert bc.classify(f"{base}.dropped_streams") == "lower"
        assert bc.classify(f"{base}.ab_mirror_overhead_ratio") == "lower"
        assert bc.classify(
            f"{base}.decode_compiles_after_warmup") == "exact"
        assert bc.classify(f"{base}.config.reload_at_step") is None

    def test_informational(self):
        assert bc.classify("serving.config.slots") is None
        assert bc.classify("config.params_m") is None
        assert bc.classify("serving_slo.loads.2x.ttft_s.n") is None
        assert bc.classify("attempts") is None
        assert bc.classify("prefill_buckets[0]") is None

    def test_control_plane_fields_direction_aware(self):
        """The ISSUE-13 serving_slo.policy block: the deltas grade
        (speedup higher, goodput_delta higher, the per-variant p99
        lower), the activity counts (how often the policy preempted /
        shed) are workload shape — informational, never graded."""
        base = "serving_slo.policy"
        assert bc.classify(f"{base}.hp_ttft_p99_speedup") == "higher"
        assert bc.classify(f"{base}.goodput_delta") == "higher"
        assert bc.classify(f"{base}.fifo.hp_ttft_p99_s") == "lower"
        assert bc.classify(f"{base}.policy.hp_ttft_p99_s") == "lower"
        assert bc.classify(f"{base}.policy.goodput") == "higher"
        for count in ("preempted", "resumed", "shed", "hp_served",
                      "completed"):
            assert bc.classify(f"{base}.policy.{count}") is None, count

    def test_fleet_family_direction_aware(self):
        """The ISSUE-17 serving_fleet block: failover latency and
        dropped/shed streams grade lower, the replica-loss throughput
        ratio and the goodput delta grade higher, the resume count is
        workload shape."""
        base = "serving_fleet"
        assert bc.classify(f"{base}.ok") == "exact_higher"
        assert bc.classify(f"{base}.failover_latency_s") == "lower"
        assert bc.classify(f"{base}.dropped_streams") == "lower"
        assert bc.classify(f"{base}.throughput_vs_baseline") == "higher"
        assert bc.classify(f"{base}.goodput_delta") == "higher"
        assert bc.classify(f"{base}.decode_compiles") == "exact"
        assert bc.classify(f"{base}.config.kill_step") is None
        assert bc.classify(f"{base}.resumed") is None

    def test_rollout_family_direction_aware(self):
        """The ISSUE-18 serving_rollout block: the wall, the swap
        pause, the verdict latency and dropped streams grade lower;
        halt/abort/rollback counts are GRADED outcomes inside this
        family (zero-baseline: any new one is a regression) but not
        elsewhere; the canary arm counts are workload shape."""
        base = "serving_rollout"
        assert bc.classify(f"{base}.ok") == "exact_higher"
        assert bc.classify(f"{base}.rollout_wall_s") == "lower"
        assert bc.classify(f"{base}.swap_pause_s_max") == "lower"
        assert bc.classify(f"{base}.verdict_latency_s") == "lower"
        assert bc.classify(f"{base}.dropped_streams") == "lower"
        for graded in ("halts", "aborts", "rollbacks", "pause"):
            assert bc.classify(f"{base}.{graded}") == "lower", graded
            assert bc.classify(f"serving_fleet.{graded}") is None, graded
        assert bc.classify("serving_slo.halts") is None
        assert bc.classify(f"{base}.decode_compiles") == "exact"
        assert bc.classify(f"{base}.canary_completed") is None
        assert bc.classify(f"{base}.replicas") is None
        assert bc.classify(f"{base}.shed") is None
        assert bc.classify(f"{base}.config.canary_window_steps") is None

    def test_quant_family_direction_aware(self):
        """The ISSUE-19 serving_quant block: agreement and the
        streams-per-GB capacity ratio grade higher, the logit drift
        and cache bytes/token grade lower, the bar booleans flip
        zero-tolerance, and compiles stay zero-tolerance — outside
        the family the same leaf names stay unclassified."""
        base = "serving_quant"
        assert bc.classify(f"{base}.ok") == "exact_higher"
        assert bc.classify(f"{base}.agreement") == "higher"
        assert bc.classify(f"{base}.capacity_ratio") == "higher"
        assert bc.classify(f"{base}.max_logit_error") == "lower"
        assert bc.classify(f"{base}.int8.kv_bytes_per_token") == "lower"
        assert bc.classify(f"{base}.fp32.kv_bytes_per_token") == "lower"
        assert bc.classify(f"{base}.int8.decode_ms_per_token") == "lower"
        assert bc.classify(f"{base}.int8.decode_compiles") == "exact"
        assert bc.classify(f"{base}.agreement_ok") == "exact_higher"
        assert bc.classify(f"{base}.capacity_ok") == "exact_higher"
        assert bc.classify(f"{base}.config.slots") is None
        # the override is family-scoped: the same names elsewhere are
        # ungraded (agreement/bytes-per-token mean nothing generically)
        assert bc.classify("serving.agreement") is None
        assert bc.classify("serving.kv_bytes_per_token") is None
        assert bc.classify("serving_slo.max_logit_error") is None

    def test_quant_regressions_flagged(self):
        worse = _mutated(**{"serving_quant.agreement": 0.80,
                            "serving_quant.capacity_ratio": 1.5,
                            "serving_quant.max_logit_error": 0.40,
                            "serving_quant.int8.kv_bytes_per_token":
                                2400.0,
                            "serving_quant.int8.decode_compiles": 2})
        kinds = _kinds(bc.compare(GOLDEN_OLD, worse))
        assert kinds["serving_quant.agreement"] == "regression"
        assert kinds["serving_quant.capacity_ratio"] == "regression"
        assert kinds["serving_quant.max_logit_error"] == "regression"
        assert kinds["serving_quant.int8.kv_bytes_per_token"] == \
            "regression"
        # a new compile of a quant program family is a retrace, never
        # noise
        assert kinds["serving_quant.int8.decode_compiles"] == "regression"
        flip = _mutated(**{"serving_quant.agreement_ok": False})
        assert _kinds(bc.compare(GOLDEN_OLD, flip))[
            "serving_quant.agreement_ok"] == "regression"
        better = _mutated(**{"serving_quant.max_logit_error": 0.01,
                             "serving_quant.capacity_ratio": 4.5})
        kinds = _kinds(bc.compare(GOLDEN_OLD, better))
        assert kinds["serving_quant.max_logit_error"] == "improvement"
        assert kinds["serving_quant.capacity_ratio"] == "improvement"

    def test_obs_fleet_family_direction_aware(self):
        """The ISSUE-20 obs_fleet block: the instrumented/bare overhead
        ratio and the alert-eval/trace-export walls grade lower, alert
        activity counts (rules firing at drain end, ledger transitions,
        requests recorded) are chaos workload shape — informational
        inside the family, and untouched elsewhere."""
        base = "obs_fleet"
        assert bc.classify(f"{base}.ok") == "exact_higher"
        assert bc.classify(f"{base}.overhead_ratio") == "lower"
        assert bc.classify(f"{base}.bare_wall_s") == "lower"
        assert bc.classify(f"{base}.instrumented_wall_s") == "lower"
        assert bc.classify(f"{base}.alert_eval_us_per_step") == "lower"
        assert bc.classify(f"{base}.trace_export_ms") == "lower"
        assert bc.classify(f"{base}.decode_compiles") == "exact"
        for count in ("alerts_firing", "alert_transitions",
                      "traced_requests"):
            assert bc.classify(f"{base}.{count}") is None, count
        assert bc.classify(f"{base}.config.n_rules") is None

    def test_shed_graded_only_inside_fleet_family(self):
        """``shed`` is a workload-shape activity count everywhere else
        (the policy/SLO blocks) but a GRADED loss inside serving_fleet:
        streams the fleet dropped must trend down."""
        assert bc.classify("serving_fleet.shed") == "lower"
        assert bc.classify("serving_slo.policy.policy.shed") is None
        assert bc.classify("serving_reload.shed") is None

    def test_policy_regression_and_improvement_graded(self):
        old = {"serving_slo": {"policy": {"hp_ttft_p99_speedup": 5.0,
                                          "goodput_delta": 0.1,
                                          "policy": {"preempted": 2}}}}
        worse = {"serving_slo": {"policy": {"hp_ttft_p99_speedup": 1.0,
                                            "goodput_delta": 0.1,
                                            "policy": {"preempted": 9}}}}
        kinds = _kinds(bc.compare(old, worse))
        assert kinds["serving_slo.policy.hp_ttft_p99_speedup"] == \
            "regression"
        # the activity count changed but is informational
        assert kinds.get("serving_slo.policy.policy.preempted") == "info"


class TestFlatten:
    def test_nested_paths_and_lists(self):
        leaves = dict((leaf.path, leaf.value)
                      for leaf in bc.flatten({"a": {"b": [1, 2]},
                                              "ok": True, "s": "x"}))
        assert leaves == {"a.b[0]": 1.0, "a.b[1]": 2.0, "ok": 1.0}


class TestCompare:
    def test_identical_is_clean(self):
        findings = bc.compare(GOLDEN_OLD, GOLDEN_OLD)
        assert not findings

    def test_latency_regression_flagged(self):
        new = _mutated(**{"serving.decode_ms_per_token": 5.0})  # +25%
        kinds = _kinds(bc.compare(GOLDEN_OLD, new))
        assert kinds["serving.decode_ms_per_token"] == "regression"

    def test_within_tolerance_passes(self):
        new = _mutated(**{"serving.decode_ms_per_token": 4.3})  # +7.5%
        assert not bc.compare(GOLDEN_OLD, new)

    def test_throughput_drop_flagged_and_direction_aware(self):
        new = _mutated(value=800.0)                             # -20%
        kinds = _kinds(bc.compare(GOLDEN_OLD, new))
        assert kinds["value"] == "regression"
        up = _mutated(**{"serving.decode_ms_per_token": 3.0})   # faster
        kinds = _kinds(bc.compare(GOLDEN_OLD, up))
        assert kinds["serving.decode_ms_per_token"] == "improvement"

    def test_p99_and_goodput_graded(self):
        worse = json.loads(json.dumps(GOLDEN_OLD))
        worse["serving_slo"]["loads"]["2x"]["ttft_s"]["p99"] = 0.2
        worse["serving_slo"]["loads"]["2x"]["goodput"] = 0.5
        kinds = _kinds(bc.compare(GOLDEN_OLD, worse))
        assert kinds["serving_slo.loads.2x.ttft_s.p99"] == "regression"
        assert kinds["serving_slo.loads.2x.goodput"] == "regression"

    def test_compile_count_zero_tolerance(self):
        new = _mutated(**{"serving.decode_compiles_after_warmup": 2})
        kinds = _kinds(bc.compare(GOLDEN_OLD, new))
        assert kinds["serving.decode_compiles_after_warmup"] == "regression"
        fewer = _mutated(**{"serving.decode_compiles_after_warmup": 0})
        kinds = _kinds(bc.compare(GOLDEN_OLD, fewer))
        assert kinds["serving.decode_compiles_after_warmup"] == "improvement"

    def test_ok_flip_is_regression(self):
        new = _mutated(**{"serving.ok": False})
        kinds = _kinds(bc.compare(GOLDEN_OLD, new))
        assert kinds["serving.ok"] == "regression"

    def test_reload_regressions_flagged(self):
        worse = _mutated(**{"serving_reload.swap_pause_ms": 4.0,
                            "serving_reload.dropped_streams": 1,
                            "serving_reload.ab_mirror_overhead_ratio": 1.4})
        kinds = _kinds(bc.compare(GOLDEN_OLD, worse))
        assert kinds["serving_reload.swap_pause_ms"] == "regression"
        # zero-baseline: ANY dropped stream is outside tolerance
        assert kinds["serving_reload.dropped_streams"] == "regression"
        assert (kinds["serving_reload.ab_mirror_overhead_ratio"]
                == "regression")
        flip = _mutated(**{"serving_reload.ok": False})
        assert _kinds(bc.compare(GOLDEN_OLD, flip))[
            "serving_reload.ok"] == "regression"

    def test_fleet_regressions_flagged(self):
        worse = _mutated(**{"serving_fleet.failover_latency_s": 0.05,
                            "serving_fleet.throughput_vs_baseline": 0.5,
                            "serving_fleet.shed": 2,
                            "serving_fleet.dropped_streams": 1})
        kinds = _kinds(bc.compare(GOLDEN_OLD, worse))
        assert kinds["serving_fleet.failover_latency_s"] == "regression"
        assert kinds["serving_fleet.throughput_vs_baseline"] == \
            "regression"
        # zero-baseline: ANY newly shed or dropped stream is outside
        # tolerance
        assert kinds["serving_fleet.shed"] == "regression"
        assert kinds["serving_fleet.dropped_streams"] == "regression"
        flip = _mutated(**{"serving_fleet.ok": False})
        assert _kinds(bc.compare(GOLDEN_OLD, flip))[
            "serving_fleet.ok"] == "regression"
        better = _mutated(**{"serving_fleet.failover_latency_s": 0.01,
                             "serving_fleet.goodput_delta": 0.5})
        kinds = _kinds(bc.compare(GOLDEN_OLD, better))
        assert kinds["serving_fleet.failover_latency_s"] == "improvement"
        assert kinds["serving_fleet.goodput_delta"] == "improvement"

    def test_rollout_regressions_flagged(self):
        worse = _mutated(**{"serving_rollout.halts": 1,
                            "serving_rollout.rollbacks": 3,
                            "serving_rollout.dropped_streams": 1,
                            "serving_rollout.swap_pause_s_max": 0.01,
                            "serving_rollout.rollout_wall_s": 3.0})
        kinds = _kinds(bc.compare(GOLDEN_OLD, worse))
        # zero-baseline: ANY new halt / rollback / dropped stream is
        # outside tolerance
        assert kinds["serving_rollout.halts"] == "regression"
        assert kinds["serving_rollout.rollbacks"] == "regression"
        assert kinds["serving_rollout.dropped_streams"] == "regression"
        assert kinds["serving_rollout.swap_pause_s_max"] == "regression"
        assert kinds["serving_rollout.rollout_wall_s"] == "regression"
        flip = _mutated(**{"serving_rollout.ok": False})
        assert _kinds(bc.compare(GOLDEN_OLD, flip))[
            "serving_rollout.ok"] == "regression"
        faster = _mutated(**{"serving_rollout.verdict_latency_s": 0.1})
        assert _kinds(bc.compare(GOLDEN_OLD, faster))[
            "serving_rollout.verdict_latency_s"] == "improvement"

    def test_obs_fleet_regressions_flagged(self):
        worse = _mutated(**{"obs_fleet.overhead_ratio": 1.30,
                            "obs_fleet.alert_eval_us_per_step": 200.0,
                            "obs_fleet.trace_export_ms": 2.0,
                            "obs_fleet.decode_compiles": 4,
                            "obs_fleet.alerts_firing": 3,
                            "obs_fleet.alert_transitions": 7})
        kinds = _kinds(bc.compare(GOLDEN_OLD, worse))
        assert kinds["obs_fleet.overhead_ratio"] == "regression"
        assert kinds["obs_fleet.alert_eval_us_per_step"] == "regression"
        assert kinds["obs_fleet.trace_export_ms"] == "regression"
        # a new compile under instrumentation is a retrace, never noise
        assert kinds["obs_fleet.decode_compiles"] == "regression"
        # alert activity is chaos workload shape, not a graded rate
        assert kinds["obs_fleet.alerts_firing"] == "info"
        assert kinds["obs_fleet.alert_transitions"] == "info"
        flip = _mutated(**{"obs_fleet.ok": False})
        assert _kinds(bc.compare(GOLDEN_OLD, flip))[
            "obs_fleet.ok"] == "regression"
        better = _mutated(**{"obs_fleet.overhead_ratio": 0.93})
        assert _kinds(bc.compare(GOLDEN_OLD, better))[
            "obs_fleet.overhead_ratio"] == "improvement"

    def test_missing_graded_metric_flagged(self):
        new = json.loads(json.dumps(GOLDEN_OLD))
        del new["serving"]["decode_ms_per_token"]
        kinds = _kinds(bc.compare(GOLDEN_OLD, new))
        assert kinds["serving.decode_ms_per_token"] == "missing"

    def test_config_change_is_informational(self):
        new = _mutated(**{"serving.config.slots": 16})
        findings = bc.compare(GOLDEN_OLD, new)
        assert _kinds(findings)["serving.config.slots"] == "info"
        assert all(f.kind == "info" for f in findings)

    def test_tolerance_override(self):
        new = _mutated(**{"serving.decode_ms_per_token": 4.3})  # +7.5%
        findings = bc.compare(GOLDEN_OLD, new,
                              tol_overrides={r"decode_ms": 0.05})
        assert _kinds(findings)["serving.decode_ms_per_token"] == \
            "regression"

    def test_regressions_sort_first(self):
        new = _mutated(**{"serving.decode_ms_per_token": 10.0,
                          "step_time_ms": 30.0})
        findings = bc.compare(GOLDEN_OLD, new)
        assert findings[0].kind == "regression"
        assert findings[-1].kind == "improvement"


class TestMain:
    def _write(self, tmp_path, old, new):
        po, pn = tmp_path / "BENCH_r1.json", tmp_path / "BENCH_r2.json"
        po.write_text(json.dumps(old))
        pn.write_text(json.dumps(new))
        return str(po), str(pn)

    def test_exit_zero_when_clean(self, tmp_path, capsys):
        po, pn = self._write(tmp_path, GOLDEN_OLD, GOLDEN_OLD)
        assert bc.main([po, pn]) == 0
        assert "0 regression(s)" in capsys.readouterr().out

    def test_exit_nonzero_on_regression(self, tmp_path, capsys):
        po, pn = self._write(tmp_path, GOLDEN_OLD,
                             _mutated(value=500.0))
        assert bc.main([po, pn]) == 1
        out = capsys.readouterr().out
        assert "regression" in out and "value" in out

    def test_tol_flag(self, tmp_path):
        po, pn = self._write(tmp_path, GOLDEN_OLD,
                             _mutated(**{"step_time_ms": 54.0}))  # +8%
        assert bc.main([po, pn]) == 0
        assert bc.main([po, pn, "--tol", "0.05"]) == 1

    def test_newest_bench_files_by_round(self, tmp_path):
        for r in (2, 10, 1):
            (tmp_path / f"BENCH_r{r}.json").write_text("{}")
        old, new = bc.newest_bench_files(str(tmp_path))
        assert old.endswith("BENCH_r2.json")
        assert new.endswith("BENCH_r10.json")
        with pytest.raises(FileNotFoundError):
            bc.newest_bench_files(str(tmp_path / "empty"))
